"""Pallas paged decode attention.

The TPU-native replacement for vLLM's PagedAttention CUDA kernel (SURVEY
§2.3 row 1; §7 hard-part 1). Semantics match
``ops/attention.py::paged_attention`` (the XLA reference) and are pinned by
tests/test_pallas.py.

Why a kernel at all: the XLA path materializes every slot's logical KV
([B, S_max, n_kv, d]) in HBM via gather before the matmul — decode reads
the KV pool twice (gather write + matmul read). This kernel DMAs each
slot's pages HBM→VMEM once and attends in-place:

- ``PrefetchScalarGridSpec`` prefetches the page table and lengths into
  SMEM so DMA source addresses are computable before the body runs.
- The page pool is **head-major** [n_kv, P, page, d] (engine/cache.py), so
  each (head, page) slice is one contiguous aligned [page, d] block — a
  single DMA with no sublane-tile slicing (a head-minor pool layout is
  rejected by Mosaic: slicing n_kv to 1 in the tiled sublane slot).
- grid = (B, n_kv); each program owns one slot x one kv head: it issues
  one async DMA per page (unused table entries point at the reserved
  trash page 0 — uniform DMA pattern, garbage masked out), waits once,
  then computes the whole group's attention with two MXU matmuls
  ([group, d] x [d, S] and [group, S] x [S, d]) in f32.
- K/V stream through VMEM scratch ([S_max, d] each: 32 pages x 64 x 128
  x bf16 = 512 KB — well under the ~16 MB budget).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llms_on_kubernetes_tpu.ops.attention import NEG_INF, softcap


def _paged_kernel(
    page_table_ref,   # SMEM [B, pages_per_seq] (scalar prefetch)
    lengths_ref,      # SMEM [B]                (scalar prefetch)
    q_ref,            # VMEM [1, 1, group, d]
    k_hbm,            # ANY  [n_kv, P, page, d] (head-major pool)
    v_hbm,            # ANY  [n_kv, P, page, d]
    o_ref,            # VMEM [1, 1, group, d]
    k_buf,            # VMEM [S, d] scratch
    v_buf,            # VMEM [S, d] scratch
    sems,             # DMA semaphores [2, pages_per_seq]
    *,
    scale: float,
    sliding_window: Optional[int],
    attn_softcap: Optional[float],
    page_size: int,
    pages_per_seq: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    S = pages_per_seq * page_size
    length = lengths_ref[b]
    # LENGTH-BOUNDED DMA: only pages actually covering this slot's tokens
    # are fetched. A slot 100 tokens into a 2048-token window must not pay
    # 20x its KV bandwidth (the full-table DMA was the decode step's
    # biggest HBM consumer at long windows). Skipped regions of the
    # scratch stay stale; every key beyond `length` is masked to NEG_INF
    # before the softmax, so stale lanes never contribute.
    n_pages = (length + page_size - 1) // page_size

    # one contiguous [page, d] DMA per page per K/V
    for i in range(pages_per_seq):
        @pl.when(i < n_pages)
        def _start(i=i):
            page_id = page_table_ref[b, i]
            pltpu.make_async_copy(
                k_hbm.at[h, page_id],
                k_buf.at[pl.ds(i * page_size, page_size), :],
                sems.at[0, i],
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[h, page_id],
                v_buf.at[pl.ds(i * page_size, page_size), :],
                sems.at[1, i],
            ).start()
    for i in range(pages_per_seq):
        @pl.when(i < n_pages)
        def _wait(i=i):
            pltpu.make_async_copy(
                k_hbm.at[h, page_table_ref[b, i]],
                k_buf.at[pl.ds(i * page_size, page_size), :],
                sems.at[0, i],
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[h, page_table_ref[b, i]],
                v_buf.at[pl.ds(i * page_size, page_size), :],
                sems.at[1, i],
            ).wait()

    q = q_ref[0, 0].astype(jnp.float32)                # [group, d]
    k = k_buf[:].astype(jnp.float32)                   # [S, d]
    v = v_buf[:].astype(jnp.float32)
    # stale (un-DMA'd) V rows must be zeroed: the p @ v matmul multiplies
    # masked-out (zero) probabilities by them, and 0 * NaN = NaN. K needs
    # no fix ONLY because the mask below is a substitutive jnp.where that
    # REPLACES garbage logits wholesale — an additive `logits + NEG_INF`
    # formulation would let stale-K NaNs through (NaN + c = NaN).
    v = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0) < length, v, 0.0)

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [group, S]
    logits = softcap(logits, attn_softcap)

    group = q.shape[0]
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (group, S), 1)
    mask = k_pos < length
    if sliding_window is not None:
        mask &= k_pos > (length - 1) - sliding_window
    logits = jnp.where(mask, logits, NEG_INF)

    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / denom
    o_ref[0, 0] = o.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "sliding_window", "attn_softcap", "interpret")
)
def pallas_paged_attention(
    q: jnp.ndarray,            # [B, n_q, d]
    k_pages: jnp.ndarray,      # [n_kv, P, page, d] (head-major pool)
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,   # [B, pages_per_seq] int32
    lengths: jnp.ndarray,      # [B] int32 (incl. current token)
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    attn_softcap: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    B, n_q, d = q.shape
    n_kv, P, page_size, _ = k_pages.shape
    pages_per_seq = page_table.shape[1]
    S = pages_per_seq * page_size
    group = n_q // n_kv

    kernel = functools.partial(
        _paged_kernel,
        scale=scale, sliding_window=sliding_window,
        attn_softcap=attn_softcap,
        page_size=page_size, pages_per_seq=pages_per_seq,
    )
    # [B, n_kv, group, d]: the block's minor two dims are (group, d), both
    # equal to the full axis — satisfies Mosaic's (8, 128)-or-full-dim rule
    # for any group size (the flat [B, n_q, d] layout did not).
    qg = q.reshape(B, n_kv, group, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, group, d), lambda b, h, *_: (b, h, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d), lambda b, h, *_: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((S, d), k_pages.dtype),
            pltpu.VMEM((S, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, pages_per_seq)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_kv, group, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(B, n_q, d)

"""Normalization ops.

Elementwise ops like RMSNorm are HBM-bandwidth bound on TPU; they are written
so XLA fuses them into the neighbouring matmuls (single pass over the
activation), with the variance accumulated in float32 regardless of the
activation dtype — the same numerics HF/vLLM use, which matters for parity
with checkpoints served by the reference stack's vLLM image.
"""

from __future__ import annotations

import jax.lax as lax
import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    *,
    style: str = "llama",
) -> jnp.ndarray:
    """RMSNorm over the last axis.

    style="llama": ``normalize(x) * w``  (Llama/Mistral/Qwen/Phi)
    style="gemma": ``normalize(x) * (1 + w)``  (Gemma stores weight-1)
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if style == "gemma":
        w = 1.0 + w
    return (normed * w).astype(dtype)

"""Ring attention: context parallelism for long sequences.

The long-context capability the reference stack entirely lacked (SURVEY §5
"Long-context / sequence parallelism: entirely absent") and the build plan
reserves as the CP extension (§7.3). Design follows blockwise/ring
attention: the sequence is sharded over the mesh's ``seq`` axis; each
device keeps its query block resident and K/V blocks rotate around the
ring via ``jax.lax.ppermute`` (XLA lowers neighbour permutes to ICI
point-to-point transfers), overlapping each hop with the local blockwise
attention. Online-softmax statistics (running max m, normalizer l) make
the blockwise accumulation exact, not approximate.

Memory: each device holds T/R of the sequence; attention scratch is
[T_local, T_local] per head pair instead of [T, T] — an R× memory saving,
which is what makes 128k+ contexts fit.

Causal masking is positional: block origins are derived from the source
device's ring index, so the rotation order never affects the result.
``ring_prefill_attention`` wraps the shard_map; ``_ring_attention_local``
is the per-shard program (also unit-testable single-device).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from llms_on_kubernetes_tpu.ops.attention import NEG_INF, softcap
from llms_on_kubernetes_tpu.parallel.mesh import AXIS_SEQ


def _block_attend(q, k, q_origin, k_origin, lengths, *, scale,
                  attn_softcap, sliding_window):
    """Masked attention logits of a local q block vs one rotating k block.

    q: [B, Tq, n_kv, g, d]; k: [B, Tk, n_kv, d]. Returns scores
    [B, n_kv, g, Tq, Tk] masked causally by GLOBAL position, by
    pad-length, and by the optional sliding window.
    """
    B, Tq = q.shape[0], q.shape[1]
    Tk = k.shape[1]
    logits = jnp.einsum("btkgd,bskd->bkgts", q, k) * scale
    logits = softcap(logits, attn_softcap)

    q_pos = q_origin + jnp.arange(Tq, dtype=jnp.int32)[:, None]   # [Tq, 1]
    k_pos = k_origin + jnp.arange(Tk, dtype=jnp.int32)[None, :]   # [1, Tk]
    mask = k_pos <= q_pos
    if sliding_window is not None:
        mask = mask & (k_pos > q_pos - sliding_window)
    valid = k_pos[None] < lengths[:, None, None]                  # [B, 1, Tk]
    mask = mask[None] & valid
    return jnp.where(mask[:, None, None], logits, NEG_INF)


def _ring_attention_local(q, k, v, lengths, *, axis_name, scale,
                          attn_softcap, sliding_window):
    """Per-shard ring attention body (runs under shard_map).

    q/k/v: [B, T_local, heads, d] — this device's sequence chunk.
    lengths: [B] GLOBAL true lengths.
    """
    B, T, n_q, d = q.shape
    n_kv = k.shape[2]
    g = n_q // n_kv
    R = jax.lax.psum(1, axis_name)           # ring size
    me = jax.lax.axis_index(axis_name)
    q_origin = me * T

    qf = q.reshape(B, T, n_kv, g, d).astype(jnp.float32)

    # online-softmax accumulators
    m = jnp.full((B, n_kv, g, T), NEG_INF, jnp.float32)
    l = jnp.zeros((B, n_kv, g, T), jnp.float32)
    o = jnp.zeros((B, n_kv, g, T, d), jnp.float32)
    perm = [(i, (i + 1) % R) for i in range(R)]

    def body(step, carry):
        m, l, o, kc, vc = carry
        # the block now resident came from device (me - step) mod R
        src = (me - step) % R
        scores = _block_attend(
            qf, kc.astype(jnp.float32), q_origin, src * T, lengths,
            scale=scale, attn_softcap=attn_softcap,
            sliding_window=sliding_window,
        )
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # guard fully-masked rows (NEG_INF - NEG_INF)
        p = jnp.exp(scores - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bkgts,bskd->bkgtd", p, vc.astype(jnp.float32))
        m = m_new
        # rotate K/V to the next device; overlap with this block's compute
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return m, l, o, kc, vc

    m, l, o, _, _ = jax.lax.fori_loop(0, R, body, (m, l, o, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]          # [B, n_kv, g, T, d]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, n_q, d).astype(q.dtype)


def ring_prefill_attention(
    q: jnp.ndarray,            # [B, T_global, n_q, d] (seq-sharded)
    k: jnp.ndarray,            # [B, T_global, n_kv, d]
    v: jnp.ndarray,
    lengths: jnp.ndarray,      # [B] global lengths
    mesh: Mesh,
    *,
    scale: float,
    attn_softcap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Causal prefill attention with the sequence sharded over mesh axis
    'seq'. Exact (same numerics policy as ops/attention.py); tested against
    the single-device reference on a virtual ring in tests/test_ring.py.

    Composes with tensor parallelism: the head axis stays sharded over
    'model' inside the shard_map (when it divides evenly), so CP×TP runs
    with no head all-gather — each device owns its heads' slice of its
    sequence chunk and only K/V blocks move, around the seq ring."""
    from llms_on_kubernetes_tpu.ops.shard_map_compat import shard_map
    from llms_on_kubernetes_tpu.parallel.mesh import AXIS_MODEL

    n_q, n_kv = q.shape[2], k.shape[2]
    model_size = int(mesh.shape[AXIS_MODEL])
    heads = (AXIS_MODEL if model_size > 1 and n_q % model_size == 0
             and n_kv % model_size == 0 else None)
    spec = P(None, AXIS_SEQ, heads, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=AXIS_SEQ, scale=scale,
            attn_softcap=attn_softcap, sliding_window=sliding_window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
        check=False,
    )
    return fn(q, k, v, lengths)

"""Rotary position embeddings (HF "rotate_half" convention).

Frequencies are computed on the fly from integer positions rather than from a
precomputed [max_len, dim] table: under ``jit`` XLA folds the trig into the
surrounding fusion, and avoiding the table keeps the decode step free of a
max_len-sized HBM read per layer.

Supports the llama3 long-context frequency rescaling used by Llama-3.1+
(`rope_scaling={"rope_type": "llama3", ...}` in HF configs).
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float,
    rope_scaling: Optional[dict] = None,
) -> np.ndarray:
    """Inverse frequencies [head_dim // 2], float32.

    Supported ``rope_scaling`` schemes: llama3 (Llama-3.1+) and linear
    (e.g. Gemma-3 global layers). Anything else raises — silently dropping
    a scaling scheme would serve wrong positions (see configs.from_hf_config).
    """
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
    if rope_scaling:
        kind = rope_scaling.get("rope_type", rope_scaling.get("type", "llama3"))
        if kind == "linear":
            return (inv_freq / float(rope_scaling.get("factor", 1.0))).astype(np.float32)
        if kind != "llama3":
            raise NotImplementedError(f"unsupported rope_scaling type {kind!r}")
        factor = float(rope_scaling.get("factor", 8.0))
        low = float(rope_scaling.get("low_freq_factor", 1.0))
        high = float(rope_scaling.get("high_freq_factor", 4.0))
        orig = float(rope_scaling.get("original_max_position_embeddings", 8192))
        wavelen = 2.0 * math.pi / inv_freq
        # llama3 scheme: leave high-freq alone, divide low-freq by factor,
        # smooth interpolation in between.
        smooth = (orig / wavelen - low) / (high - low)
        smooth = np.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / factor
        inv_freq = np.where(
            wavelen > orig / low,  # low frequency band
            scaled,
            np.where(
                wavelen < orig / high,  # high frequency band
                inv_freq,
                (1.0 - smooth) * scaled + smooth * inv_freq,
            ),
        )
    return inv_freq.astype(np.float32)


def apply_rope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rotate q and k.

    q: [..., T, num_heads, head_dim]
    k: [..., T, num_kv_heads, head_dim]
    positions: [..., T] int32
    inv_freq: [head_dim // 2] float32
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., T, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., T, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]

    def rot(x: jnp.ndarray) -> jnp.ndarray:
        half = x.shape[-1] // 2
        x1 = x[..., :half].astype(jnp.float32)
        x2 = x[..., half:].astype(jnp.float32)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        return out.astype(x.dtype)

    return rot(q), rot(k)


def apply_mrope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    pos3: jnp.ndarray,
    inv_freq: jnp.ndarray,
    mrope_section: tuple,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Interleaved multimodal RoPE (Qwen3-VL text decoder).

    pos3 [..., 3, T]: (temporal, height, width) position per token — all
    three equal for text tokens (then this reduces EXACTLY to apply_rope),
    spatially varying for image soft tokens. The per-axis frequency
    channels interleave as [T,H,W,T,H,W,...] up to 3*section[i] then fall
    back to the temporal axis — matching the public Qwen3-VL scheme.
    """
    angles3 = pos3[..., :, :, None].astype(jnp.float32) * inv_freq
    # [..., 3, T, hd/2] -> interleaved combined [..., T, hd/2]. The
    # channel->axis assignment is STATIC, so plain where-selects fold into
    # the surrounding fusion (no gather).
    half = inv_freq.shape[-1]
    axis_sel = np.zeros((half,), np.int32)           # default: temporal
    for dim, offset in ((1, 1), (2, 2)):             # H, W
        idx = np.arange(offset, 3 * mrope_section[dim], 3)
        axis_sel[idx[idx < half]] = dim
    angles = angles3[..., 0, :, :]
    for dim in (1, 2):
        angles = jnp.where(jnp.asarray(axis_sel == dim),
                           angles3[..., dim, :, :], angles)
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]

    def rot(x: jnp.ndarray) -> jnp.ndarray:
        half_d = x.shape[-1] // 2
        x1 = x[..., :half_d].astype(jnp.float32)
        x2 = x[..., half_d:].astype(jnp.float32)
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        return out.astype(x.dtype)

    return rot(q), rot(k)

"""Pallas blockwise (flash) prefill attention.

The TPU-native replacement for the flash-attention CUDA kernels the
reference pulled inside the vLLM image (SURVEY §2.3 row 1). Semantics match
``ops/attention.py::prefill_attention`` (the XLA reference implementation)
and are pinned by tests/test_pallas.py.

Kernel shape (v2):
- Inputs are transposed to head-major [B, H, T, d] at the wrapper so every
  block's minor two dims are (T-block, d) — Mosaic requires the last two
  block dims be multiples of (8, 128) or the full axis, which the v1
  token-major layout [B, T, H, d] violated (head axis block of 1 in the
  sublane slot fails to lower on real TPU; interpret mode hid it).
- grid = (B, n_q_heads, T // BLOCK_Q); each program owns one query block of
  one head and streams the head's full K/V through VMEM (prefill buckets
  are <= a few K tokens, so K/V fit VMEM comfortably: T=4096, d=128, bf16
  -> 1 MB each). Logits never touch HBM — the [T, T] score matrix the XLA
  path materializes per head stays in VMEM one [BLOCK_Q, T] tile at a time.
- GQA via the index map: query head h reads kv head h // group; the q-block
  index varies fastest so the same K/V block is reused across the whole
  row of q-blocks without re-fetching.
- Masking (causal + pad-length + optional sliding window) is additive in
  f32; softmax in f32 (same numerics policy as the reference impl).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llms_on_kubernetes_tpu.ops.attention import NEG_INF, softcap

BLOCK_Q = 128


def _flash_kernel(
    lengths_ref,   # SMEM [B] — true lengths (whole array, indexed by b)
    q_ref,         # VMEM [1, 1, BLOCK_Q, d]
    k_ref,         # VMEM [1, 1, T, d]
    v_ref,         # VMEM [1, 1, T, d]
    o_ref,         # VMEM [1, 1, BLOCK_Q, d]
    *,
    scale: float,
    sliding_window: Optional[int],
    attn_softcap: Optional[float],
    block_q: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    T = k_ref.shape[2]
    length = lengths_ref[b]

    q = q_ref[0, 0].astype(jnp.float32)                # [Bq, d]
    k = k_ref[0, 0].astype(jnp.float32)                # [T, d]
    v = v_ref[0, 0].astype(jnp.float32)                # [T, d]

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [Bq, T]
    logits = softcap(logits, attn_softcap)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, T), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
    mask = (k_pos <= q_pos) & (k_pos < length)
    if sliding_window is not None:
        mask &= k_pos > q_pos - sliding_window
    logits = jnp.where(mask, logits, NEG_INF)

    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / denom
    o_ref[0, 0] = o.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "sliding_window", "attn_softcap", "interpret")
)
def flash_prefill_attention(
    q: jnp.ndarray,           # [B, T, n_q, d]
    k: jnp.ndarray,           # [B, T, n_kv, d]
    v: jnp.ndarray,
    lengths: jnp.ndarray,     # [B] int32
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    attn_softcap: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    B, T, n_q, d = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    block_q = min(BLOCK_Q, T)
    assert T % block_q == 0, f"prefill bucket {T} not a multiple of {block_q}"

    # head-major layout so block minor dims are (tokens, head_dim)
    qh = jnp.swapaxes(q, 1, 2)  # [B, n_q, T, d]
    kh = jnp.swapaxes(k, 1, 2)  # [B, n_kv, T, d]
    vh = jnp.swapaxes(v, 1, 2)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale, sliding_window=sliding_window,
        attn_softcap=attn_softcap, block_q=block_q,
    )
    grid = (B, n_q, T // block_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, T, d), lambda b, h, i: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, T, d), lambda b, h, i: (b, h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, n_q, T, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qh, kh, vh)
    return jnp.swapaxes(out, 1, 2)  # back to [B, T, n_q, d]

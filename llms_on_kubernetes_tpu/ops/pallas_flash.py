"""Pallas blockwise (flash) prefill attention.

The TPU-native replacement for the flash-attention CUDA kernels the
reference pulled inside the vLLM image (SURVEY §2.3 row 1). Semantics match
``ops/attention.py::prefill_attention`` (the XLA reference implementation)
and are pinned by tests/test_pallas.py.

Kernel shape (v1):
- grid = (B, n_q_heads, T // BLOCK_Q); each program owns one query block of
  one head and streams the head's full K/V through VMEM (prefill buckets
  are <= a few K tokens, so K/V fit VMEM comfortably: T=4096, d=128, bf16
  -> 1 MB each). Logits never touch HBM — the [T, T] score matrix the XLA
  path materializes per head stays in VMEM one [BLOCK_Q, T] tile at a time.
- GQA via the index map: query head h reads kv head h // group, so the MXU
  sees per-head [BLOCK_Q, d] x [d, T] matmuls and K/V are fetched once per
  q-block, not repeated per query head in HBM.
- Masking (causal + pad-length + optional sliding window) is additive in
  f32; softmax in f32 (same numerics policy as the reference impl).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from llms_on_kubernetes_tpu.ops.attention import NEG_INF, softcap
BLOCK_Q = 128


def _flash_kernel(
    lengths_ref,   # SMEM [1, 1] — this batch row's true length
    q_ref,         # VMEM [1, BLOCK_Q, 1, d]
    k_ref,         # VMEM [1, T, 1, d]
    v_ref,         # VMEM [1, T, 1, d]
    o_ref,         # VMEM [1, BLOCK_Q, 1, d]
    *,
    scale: float,
    sliding_window: Optional[int],
    attn_softcap: Optional[float],
    block_q: int,
):
    qi = pl.program_id(2)
    T = k_ref.shape[1]
    length = lengths_ref[0, 0]

    q = q_ref[0, :, 0, :].astype(jnp.float32)          # [Bq, d]
    k = k_ref[0, :, 0, :].astype(jnp.float32)          # [T, d]
    v = v_ref[0, :, 0, :].astype(jnp.float32)          # [T, d]

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [Bq, T]
    logits = softcap(logits, attn_softcap)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, T), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
    mask = (k_pos <= q_pos) & (k_pos < length)
    if sliding_window is not None:
        mask &= k_pos > q_pos - sliding_window
    logits = jnp.where(mask, logits, NEG_INF)

    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / denom
    o_ref[0, :, 0, :] = o.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "sliding_window", "attn_softcap", "interpret")
)
def flash_prefill_attention(
    q: jnp.ndarray,           # [B, T, n_q, d]
    k: jnp.ndarray,           # [B, T, n_kv, d]
    v: jnp.ndarray,
    lengths: jnp.ndarray,     # [B] int32
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    attn_softcap: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    B, T, n_q, d = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    block_q = min(BLOCK_Q, T)
    assert T % block_q == 0, f"prefill bucket {T} not a multiple of {block_q}"

    kernel = functools.partial(
        _flash_kernel,
        scale=scale, sliding_window=sliding_window,
        attn_softcap=attn_softcap, block_q=block_q,
    )
    grid = (B, n_q, T // block_q)
    lengths2d = lengths.reshape(B, 1).astype(jnp.int32)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, h, i: (b, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, 1, d), lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((1, T, 1, d), lambda b, h, i: (b, 0, h // group, 0)),
            pl.BlockSpec((1, T, 1, d), lambda b, h, i: (b, 0, h // group, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, d), lambda b, h, i: (b, i, h, 0)),
        out_shape=jax.ShapeDtypeStruct((B, T, n_q, d), q.dtype),
        interpret=interpret,
    )(lengths2d, q, k, v)

"""Weight-only int8 quantization for serving.

The reference's default deployment serves quantized checkpoints — FP8-Dynamic
gemma-3-27b and AWQ-8bit Qwen3 (reference vllm-models/helm-chart/
values.yaml:2-12) — with dequantizing matmul kernels pulled in the vLLM
image. The TPU-native equivalent is weight-only symmetric int8 with
per-output-channel scales, dequantized on the fly inside the matmul:

- ``QTensor`` holds int8 data in the original weight shape plus a float32
  scale broadcastable against it (``keepdims`` over the reduced axes). It is
  a pytree, so layer-stacked quantized weights slice correctly under
  ``lax.scan`` and shard under ``device_put`` like any other param.
- ``qeinsum`` dequantizes inline: the int8->bf16 convert and the scale
  multiply are elementwise ops on the dot operand, which XLA fuses into the
  MXU matmul's operand load — the bf16 weight never materializes in HBM.
  Weights stream from HBM at 1 byte/param: on a bandwidth-bound decode step
  this halves the per-token weight traffic vs bf16.

Per-output-channel symmetric quantization is exact under the matmul in the
sense that dequantizing before or after the contraction is algebraically
identical, so accuracy loss comes only from the int8 rounding of each
channel (relative error <= 1/254 per weight).
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 weight + broadcastable per-channel scale."""

    def __init__(self, data: jnp.ndarray, scale: jnp.ndarray):
        self.data = data
        self.scale = scale

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def dequantize(self, dtype=jnp.bfloat16) -> jnp.ndarray:
        return (self.data.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(shape={tuple(self.data.shape)}, scale={tuple(self.scale.shape)})"


def quantize(w, reduce_axes: tuple[int, ...]) -> QTensor:
    """Symmetric int8 quantization; scale computed over ``reduce_axes``
    (the contraction/input axes) with keepdims, so out channels each get
    their own scale and the scale broadcasts against ``data``.

    Numpy inputs are quantized IN HOST RAM (numpy ops) — checkpoint loading
    must not commit the unquantized fp32 weight to a device before
    ``shard_params`` distributes the int8 result (a 70B layer stack would
    OOM a single chip). Device arrays stay on device.
    """
    import numpy as np

    xp = np if isinstance(w, np.ndarray) else jnp
    wf = xp.asarray(w, dtype=xp.float32)
    amax = xp.max(xp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = xp.where(amax > 0, amax / 127.0, xp.float32(1.0))
    data = xp.clip(xp.round(wf / scale), -127, 127).astype(xp.int8)
    return QTensor(data, scale)


@jax.tree_util.register_pytree_node_class
class GroupQTensor:
    """Group-wise quantized weight, executed NATIVELY (AWQ int4/int8).

    The serving-exact representation of an AWQ 'gemm' tensor — no
    re-quantization to int8 per-channel (round-3 verdict: that was an
    accuracy approximation, vLLM executes the group format natively).

    data        [..., G, gs, O] int4 (or int8): CENTERED quantized values
                (q - 2^(bits-1)); int4 storage streams 0.5 byte/param
    scale       [..., G, O] float32
    zero_scaled [..., G, O] float32 = scale * (zero - 2^(bits-1))
    out_shape   logical output dims (prod == O); the logical weight is
                w[i, o] = data[g, i % gs, o] * scale[g, o]
                          - zero_scaled[g, o],  g = i // gs
    Leading axes (the engine's layer stack) ride along; lax.scan slices
    them per layer like any other leaf.
    """

    def __init__(self, data, scale, zero_scaled, out_shape: tuple):
        self.data = data
        self.scale = scale
        self.zero_scaled = zero_scaled
        self.out_shape = tuple(out_shape)

    @property
    def shape(self):  # logical [in, *out_shape]
        g, gs = self.data.shape[-3], self.data.shape[-2]
        return tuple(self.data.shape[:-3]) + (g * gs,) + self.out_shape

    def dequantize(self, dtype=jnp.bfloat16) -> jnp.ndarray:
        w = (self.data.astype(jnp.float32) * self.scale[..., None, :]
             - self.zero_scaled[..., None, :])
        lead = self.data.shape[:-3]
        g, gs, o = self.data.shape[-3:]
        return w.reshape(lead + (g * gs,) + self.out_shape).astype(dtype)

    def tree_flatten(self):
        return (self.data, self.scale, self.zero_scaled), self.out_shape

    @classmethod
    def tree_unflatten(cls, out_shape, children):
        return cls(*children, out_shape)

    def __repr__(self):
        return (f"GroupQTensor(data={tuple(self.data.shape)} "
                f"{self.data.dtype}, out={self.out_shape})")


def awq_group_tensors(qweight, qzeros, scales, bits: int = 4,
                      storage=None, out_shape=None) -> GroupQTensor:
    """AWQ gemm tensors -> a GroupQTensor (native execution; exact).

    qweight int32 [in, out*bits/32], qzeros int32 [G, out*bits/32],
    scales f16/f32 [G, out]. ``storage`` overrides the packed dtype
    (default: int4 for 4-bit — half the HBM stream of int8 — int8 for
    8-bit; env LLMK_AWQ_STORAGE=int8 forces int8 if a backend lacks
    int4 support). Leaves are HOST numpy arrays (ml_dtypes int4) so
    checkpoint loading stacks layers in host RAM before device placement
    (same policy as ``quantize``)."""
    import ml_dtypes
    import numpy as np

    q = _awq_unpack(np.asarray(qweight, np.int32), bits)   # [in, out]
    z = _awq_unpack(np.asarray(qzeros, np.int32), bits)    # [G, out]
    G, O = z.shape
    gs = q.shape[0] // G
    center = 1 << (bits - 1)
    if storage is None:
        storage = os.environ.get("LLMK_AWQ_STORAGE")
    if storage is None:
        # int4 storage halves the weight HBM stream, but the current TPU
        # runtime rejects int4 arrays outright (probed: transfer/convert
        # both fail); int8 keeps the group math EXACT at the int8-class
        # stream. CPU defaults to int4 so the packed path stays tested;
        # LLMK_AWQ_STORAGE=int4 opts in on runtimes that support it.
        storage = ("int8" if bits == 8 or jax.default_backend() == "tpu"
                   else "int4")
    dt = ml_dtypes.int4 if storage == "int4" else np.int8
    s = np.asarray(scales, np.float32)
    return GroupQTensor(
        (q - center).astype(np.int8).reshape(G, gs, O).astype(dt),
        s,
        (z.astype(np.float32) - center) * s,
        out_shape=tuple(out_shape) if out_shape is not None else (O,),
    )


def group_qeinsum(eq: str, x: jnp.ndarray, w: GroupQTensor) -> jnp.ndarray:
    """einsum against a group-quantized weight, contraction grouped.

    Exact algebra (no dequantized weight ever materializes in HBM):
        y[., o] = sum_g  s[g, o] * (x[., g, :] @ data[g, :, o])
                - sum_g  zs[g, o] * sum_i x[., g, i]
    computed as a ``lax.scan`` over groups with an f32 accumulator, so
    peak memory is one [batch, O] buffer and the weight streams once at
    its packed width. Decoder contract (asserted): the weight's
    contraction axis is its FIRST logical axis and x's LAST.
    """
    lhs, out_sub = eq.split("->")
    x_sub, w_sub = lhs.split(",")
    n_con = len(w_sub) - len(w.out_shape)
    assert x_sub[-n_con:] == w_sub[:n_con] and all(
        c not in out_sub for c in w_sub[:n_con]), (
        f"group_qeinsum: {eq} does not contract the weight's leading axes")
    G, gs, O = w.data.shape[-3:]
    lead = x.shape[:-n_con]
    xg = x.reshape(lead + (G, gs))
    xs_x = jnp.moveaxis(xg, -2, 0)                     # [G, ..., gs]

    def body(acc, per_g):
        xg_, qg, sg, zg = per_g                        # [..., gs] / [gs, O]
        part = jnp.einsum("...i,io->...o", xg_, qg.astype(x.dtype),
                          preferred_element_type=jnp.float32)
        xsum = xg_.sum(axis=-1).astype(jnp.float32)[..., None]
        return acc + part * sg - xsum * zg, None

    acc0 = jnp.zeros(lead + (O,), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0,
                          (xs_x, w.data, w.scale, w.zero_scaled))
    return acc.reshape(lead + w.out_shape).astype(x.dtype)


def qeinsum(eq: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """einsum where the second operand may be a QTensor.

    The per-output-channel scale is applied to the einsum OUTPUT, not the
    weight: scales live only on non-contracted axes (keepdims policy), so
    ``einsum(x, w_int8 * s) == einsum(x, w_int8) * s_broadcast`` exactly —
    and the multiply touches the small activation tensor instead of the
    weight, guaranteeing the dequantized weight never materializes in HBM
    no matter how XLA schedules the fusion. Only the int8->bf16 convert
    rides on the weight read (fused into the MXU operand load).
    """
    if isinstance(w, GroupQTensor):
        return group_qeinsum(eq, x, w)
    if not isinstance(w, QTensor):
        return jnp.einsum(eq, x, w)
    lhs, out = eq.split("->")
    _, w_sub = lhs.split(",")
    # scale's non-1 dims sit on w's non-contracted axes, which appear in
    # the output in the same relative order (true for every decoder eq)
    out_shape = tuple(
        w.scale.shape[w_sub.index(c)] if c in w_sub else 1 for c in out
    )
    y = jnp.einsum(eq, x, w.data.astype(x.dtype))
    return (y * w.scale.reshape(out_shape).astype(x.dtype)).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pre-quantized checkpoint formats (reference values.yaml:2-12: the default
# models are gemma-3-27b-it-FP8-Dynamic, a compressed-tensors FP8
# checkpoint, and Qwen3-VL-...-AWQ, an AWQ checkpoint). Both are
# dequantized tensor-by-tensor at load and re-quantized to the TPU-native
# serving format (weight-only int8 per-output-channel, streamed at
# 1 byte/param — same on-device footprint as the source format); accuracy
# is pinned by logit-tolerance tests against a full-precision dequant.
# ---------------------------------------------------------------------------

# AutoAWQ's nibble interleave: bits [4k, 4k+4) of a packed int32 hold the
# quantized value for output channel 8*j + _AWQ_ORDER[k].
_AWQ_ORDER = (0, 2, 4, 6, 1, 3, 5, 7)


def _awq_unpack(arr, bits: int):
    """[r, c] int32 -> [r, c*pack] unpacked values with AWQ interleave."""
    import numpy as np

    if bits not in (4, 8):
        raise ValueError(f"unsupported AWQ bits={bits}")
    pack = 32 // bits
    mask = (1 << bits) - 1
    r, c = arr.shape
    out = np.empty((r, c * pack), np.int32)
    order = _AWQ_ORDER if bits == 4 else range(pack)
    for k, o in enumerate(order):
        out[:, o::pack] = (arr >> (bits * k)) & mask
    return out


def awq_dequantize(qweight: "np.ndarray", qzeros: "np.ndarray",
                   scales: "np.ndarray", bits: int = 4) -> "np.ndarray":
    """AWQ GEMM-format dequant -> float32 [in, out].

    qweight int32 [in, out*bits/32], qzeros int32 [n_groups, out*bits/32],
    scales f16/f32 [n_groups, out]; group_size = in / n_groups;
    w[i, o] = (q[i, o] - z[g(i), o]) * s[g(i), o].
    """
    import numpy as np

    q = _awq_unpack(qweight.astype(np.int32), bits)  # [in, out]
    z = _awq_unpack(qzeros.astype(np.int32), bits)   # [n_groups, out]
    n_groups = z.shape[0]
    group = q.shape[0] // n_groups
    zf = np.repeat(z, group, axis=0).astype(np.float32)
    sf = np.repeat(scales.astype(np.float32), group, axis=0)
    return (q.astype(np.float32) - zf) * sf        # [in, out]


def fp8_dequantize(weight: "np.ndarray", weight_scale) -> "np.ndarray":
    """compressed-tensors FP8 dequant -> float32 [out, in].

    weight float8_e4m3fn [out, in]; weight_scale f32 — scalar (per-tensor)
    or [out, 1] (per-channel).
    """
    import numpy as np

    w = weight.astype(np.float32)
    s = np.asarray(weight_scale, np.float32)
    if s.ndim == 1:  # [out] -> per-channel column
        s = s[:, None]
    return w * s


# ---------------------------------------------------------------------------
# Whole-model quantization
# ---------------------------------------------------------------------------

# "fp8" / "awq" declare the CHECKPOINT's format (validated against its
# quantization_config at load); serving still streams weight-only int8.
SUPPORTED_QUANTIZATIONS = (None, "int8", "fp8", "awq")

# Weight name -> contraction (input) axes of the PER-LAYER slice, offset by
# +1 for the stacked layer axis. wq [L, D, H, hd] contracts over D -> (1,).
_LAYER_REDUCE_AXES = {
    "wq": (1,), "wk": (1,), "wv": (1,),
    "wo": (1, 2),                 # [L, H, hd, D] contracts over (H, hd)
    "w_gate": None, "w_up": None, "w_down": None,  # shape-dependent (MoE)
}


def reduce_axes_for(name: str, ndim: int) -> tuple[int, ...]:
    """Contraction axes for a stacked weight — single source of the
    quantization-axis policy (used by quantize_params and the benchmark
    param generator alike)."""
    axes = _LAYER_REDUCE_AXES[name]
    if axes is not None:
        return axes
    # mlp weights: MoE [L, E, in, out]-style contracts dim 2, dense dim 1
    return (2,) if ndim == 4 else (1,)


def quantize_params(params: Params) -> Params:
    """Quantize the big matmul weights of a decoder param tree to int8.

    Embedding / lm_head / norms stay in their original dtype (the embedding
    is a gather, not a matmul, and the final logits matmul is accuracy-
    critical — same policy as the AWQ/FP8 checkpoints the reference served,
    which keep embeddings in 16-bit).
    """
    out = dict(params)
    layers = dict(params["layers"])
    for name in _LAYER_REDUCE_AXES:
        w = layers.get(name)
        if w is None or isinstance(w, QTensor):
            continue
        layers[name] = quantize(w, reduce_axes_for(name, w.ndim))
    out["layers"] = layers
    return out



@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _pattern(shape, dtype, seed: int):
    """Cheap pseudo-random fill: fused iota -> hash -> cast, so only the
    final dtype ever materializes (an 8B model's int8 weights build in
    milliseconds with ~zero temp HBM — real RNG over a device tunnel took
    minutes and doubled peak memory)."""
    n = 1
    for s in shape:
        n *= s
    x = jax.lax.iota(jnp.uint32, n) * jnp.uint32(2654435761) + jnp.uint32(seed)
    x = (x >> 8) % 255  # [0, 255)
    if jnp.dtype(dtype) == jnp.int8:
        return (x.astype(jnp.int32) - 127).astype(jnp.int8).reshape(shape)
    return ((x.astype(jnp.float32) / 127.0 - 1.0) * 0.02).astype(dtype).reshape(shape)


def random_quantized_params(cfg, key: jax.Array) -> Params:
    """Pseudo-random already-int8 params for big-model compile checks and
    weight-streaming benchmarks (values don't matter, shapes/dtypes do).

    Never materializes a full-precision weight: matmul weights are generated
    directly as int8 (+ constant scales), so Llama-3-8B fits a single 16 GB
    v5e chip (~9 GB) — the configuration the BASELINE north star benches.
    """
    from llms_on_kubernetes_tpu.models.decoder import init_params

    del key  # deterministic pattern fill; kept for API symmetry
    shapes = jax.eval_shape(lambda k: init_params(cfg, k), jax.random.key(0))
    quant_names = set(_LAYER_REDUCE_AXES)
    seed = iter(range(1, 256))
    out: Params = {}
    for section, val in shapes.items():
        if section != "layers":
            out[section] = _pattern(val.shape, val.dtype, next(seed))
    layers: Params = {}
    for name, leaf in shapes["layers"].items():
        if name in quant_names:
            data = _pattern(leaf.shape, jnp.int8, next(seed))
            axes = reduce_axes_for(name, len(leaf.shape))
            # keep leading (layer-stack) axis and out channels in the scale
            sshape = tuple(1 if i in axes else s
                           for i, s in enumerate(leaf.shape))
            layers[name] = QTensor(data, jnp.full(sshape, 1e-3, jnp.float32))
        elif name in ("attn_norm", "mlp_norm", "q_norm", "k_norm",
                      "attn_post_norm", "mlp_post_norm", "final_norm"):
            layers[name] = jnp.ones(leaf.shape, leaf.dtype)
        else:
            layers[name] = _pattern(leaf.shape, leaf.dtype, next(seed))
    out["layers"] = layers
    return out


def scale_spec(data_spec, scale_shape) -> "jax.sharding.PartitionSpec":
    """PartitionSpec for a QTensor's scale given the data's spec: kept
    (size>1) dims inherit the data's axis, reduced (size==1) dims are
    unsharded."""
    from jax.sharding import PartitionSpec as P

    dims = list(data_spec) + [None] * (len(scale_shape) - len(data_spec))
    return P(*[a if s > 1 else None for a, s in zip(dims, scale_shape)])

"""Weight-only int8 quantization for serving.

The reference's default deployment serves quantized checkpoints — FP8-Dynamic
gemma-3-27b and AWQ-8bit Qwen3 (reference vllm-models/helm-chart/
values.yaml:2-12) — with dequantizing matmul kernels pulled in the vLLM
image. The TPU-native equivalent is weight-only symmetric int8 with
per-output-channel scales, dequantized on the fly inside the matmul:

- ``QTensor`` holds int8 data in the original weight shape plus a float32
  scale broadcastable against it (``keepdims`` over the reduced axes). It is
  a pytree, so layer-stacked quantized weights slice correctly under
  ``lax.scan`` and shard under ``device_put`` like any other param.
- ``qeinsum`` dequantizes inline: the int8->bf16 convert and the scale
  multiply are elementwise ops on the dot operand, which XLA fuses into the
  MXU matmul's operand load — the bf16 weight never materializes in HBM.
  Weights stream from HBM at 1 byte/param: on a bandwidth-bound decode step
  this halves the per-token weight traffic vs bf16.

Per-output-channel symmetric quantization is exact under the matmul in the
sense that dequantizing before or after the contraction is algebraically
identical, so accuracy loss comes only from the int8 rounding of each
channel (relative error <= 1/254 per weight).
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 weight + broadcastable per-channel scale."""

    def __init__(self, data: jnp.ndarray, scale: jnp.ndarray):
        self.data = data
        self.scale = scale

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def dequantize(self, dtype=jnp.bfloat16) -> jnp.ndarray:
        return (self.data.astype(jnp.float32) * self.scale).astype(dtype)

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QTensor(shape={tuple(self.data.shape)}, scale={tuple(self.scale.shape)})"


def quantize(w, reduce_axes: tuple[int, ...]) -> QTensor:
    """Symmetric int8 quantization; scale computed over ``reduce_axes``
    (the contraction/input axes) with keepdims, so out channels each get
    their own scale and the scale broadcasts against ``data``.

    Numpy inputs are quantized IN HOST RAM (numpy ops) — checkpoint loading
    must not commit the unquantized fp32 weight to a device before
    ``shard_params`` distributes the int8 result (a 70B layer stack would
    OOM a single chip). Device arrays stay on device.
    """
    import numpy as np

    xp = np if isinstance(w, np.ndarray) else jnp
    wf = xp.asarray(w, dtype=xp.float32)
    amax = xp.max(xp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = xp.where(amax > 0, amax / 127.0, xp.float32(1.0))
    data = xp.clip(xp.round(wf / scale), -127, 127).astype(xp.int8)
    return QTensor(data, scale)


@jax.tree_util.register_pytree_node_class
class GroupQTensor:
    """Group-wise quantized weight, executed NATIVELY (AWQ int4/int8).

    The serving-exact representation of an AWQ 'gemm' tensor — no
    re-quantization to int8 per-channel (round-3 verdict: that was an
    accuracy approximation, vLLM executes the group format natively).

    data        [..., G, gs, O] int8 CENTERED quantized values
                (q - 2^(bits-1)). With ``packed=True`` the stored axis is
                gs/2: two 4-bit nibbles per int8 lane (element 2i in the
                low nibble, 2i+1 in the high nibble of lane i), so 4-bit
                weights stream 0.5 byte/param on every backend — the TPU
                runtime accepts the carrier int8 array even though it
                rejects int4 arrays outright.
    scale       [..., G, O] float32
    zero_scaled [..., G, O] float32 = scale * (zero - 2^(bits-1))
    out_shape   logical output dims (prod == O); the logical weight is
                w[i, o] = data[g, i % gs, o] * scale[g, o]
                          - zero_scaled[g, o],  g = i // gs
    group_axis  mesh axis name when the GROUP axis is sharded
                (row-parallel wo/w_down under TP): ``group_qeinsum`` then
                computes per-device partial sums over the local groups and
                psums across the axis. None when unsharded/column-parallel.
    Leading axes (the engine's layer stack) ride along; lax.scan slices
    them per layer like any other leaf. ``packed``/``group_axis`` are
    pytree AUX data — static at trace time, so the kernel specializes.
    """

    def __init__(self, data, scale, zero_scaled, out_shape: tuple,
                 packed: bool = False, group_axis=None):
        self.data = data
        self.scale = scale
        self.zero_scaled = zero_scaled
        self.out_shape = tuple(out_shape)
        self.packed = bool(packed)
        self.group_axis = group_axis

    @property
    def group_size(self):  # LOGICAL gs (stored axis is gs/2 when packed)
        return self.data.shape[-2] * (2 if self.packed else 1)

    @property
    def shape(self):  # logical [in, *out_shape]
        g = self.data.shape[-3]
        return tuple(self.data.shape[:-3]) + (g * self.group_size,) \
            + self.out_shape

    def dequantize(self, dtype=jnp.bfloat16) -> jnp.ndarray:
        data = self.data
        if self.packed:
            data = unpack_int4_lanes(jnp.asarray(data))
        w = (data.astype(jnp.float32) * self.scale[..., None, :]
             - self.zero_scaled[..., None, :])
        lead = self.data.shape[:-3]
        g, o = self.data.shape[-3], self.data.shape[-1]
        return w.reshape(lead + (g * self.group_size,)
                         + self.out_shape).astype(dtype)

    def tree_flatten(self):
        return ((self.data, self.scale, self.zero_scaled),
                (self.out_shape, self.packed, self.group_axis))

    @classmethod
    def tree_unflatten(cls, aux, children):
        if isinstance(aux, tuple) and aux and isinstance(aux[0], tuple):
            out_shape, packed, group_axis = aux
        else:  # pre-packing aux format (out_shape only)
            out_shape, packed, group_axis = aux, False, None
        return cls(*children, out_shape, packed, group_axis)

    def __repr__(self):
        return (f"GroupQTensor(data={tuple(self.data.shape)} "
                f"{self.data.dtype}, out={self.out_shape}, "
                f"packed={self.packed}, group_axis={self.group_axis})")


def pack_int4_lanes(q):
    """Centered int4-range values [..., gs, O] int8 -> [..., gs/2, O] int8
    with two's-complement nibbles lane-packed: element 2i in the low
    nibble, 2i+1 in the high nibble. Host numpy in, host numpy out
    (checkpoint loading packs before device placement)."""
    import numpy as np

    gs = q.shape[-2]
    assert gs % 2 == 0, f"group_size {gs} must be even to nibble-pack"
    u = np.asarray(q, np.int8).view(np.uint8)
    lo = u[..., 0::2, :] & np.uint8(0xF)
    hi = (u[..., 1::2, :] & np.uint8(0xF)) << np.uint8(4)
    return (lo | hi).view(np.int8)


def unpack_int4_lanes(p: jnp.ndarray) -> jnp.ndarray:
    """Device-side inverse of ``pack_int4_lanes``: [..., gsp, O] int8 ->
    [..., 2*gsp, O] int8. Shift-left then arithmetic-shift-right
    sign-extends the low nibble; a plain arithmetic shift extracts the
    high one. Both are cheap elementwise ops XLA fuses into the consuming
    matmul's operand load, so the unpacked weight never round-trips HBM.
    """
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    stacked = jnp.stack((lo, hi), axis=-2)   # [..., gsp, 2, O]
    return stacked.reshape(p.shape[:-2] + (2 * p.shape[-2], p.shape[-1]))


def awq_group_tensors(qweight, qzeros, scales, bits: int = 4,
                      storage=None, out_shape=None) -> GroupQTensor:
    """AWQ gemm tensors -> a GroupQTensor (native execution; exact).

    qweight int32 [in, out*bits/32], qzeros int32 [G, out*bits/32],
    scales f16/f32 [G, out]. ``storage`` overrides the on-device layout:
    "packed4" (the 4-bit default on every backend) lane-packs two
    nibbles per int8 byte — half the HBM stream of int8 on a carrier
    dtype every runtime accepts; "int4" keeps ml_dtypes int4 elements
    (rejected by the current TPU runtime); "int8" widens (exact, double
    the stream). Env LLMK_AWQ_STORAGE picks among the three. Leaves are
    HOST numpy arrays so checkpoint loading stacks layers in host RAM
    before device placement (same policy as ``quantize``)."""
    import ml_dtypes
    import numpy as np

    q = _awq_unpack(np.asarray(qweight, np.int32), bits)   # [in, out]
    z = _awq_unpack(np.asarray(qzeros, np.int32), bits)    # [G, out]
    G, O = z.shape
    gs = q.shape[0] // G
    center = 1 << (bits - 1)
    if storage is None:
        storage = os.environ.get("LLMK_AWQ_STORAGE")
    if storage is None:
        storage = "int8" if bits == 8 else "packed4"
    if storage not in ("int8", "int4", "packed4"):
        raise ValueError(f"unsupported AWQ storage {storage!r}")
    if storage == "packed4" and (bits != 4 or gs % 2):
        storage = "int8"  # nibble-packing needs 4-bit values, even gs
    centered = (q - center).astype(np.int8).reshape(G, gs, O)
    if storage == "packed4":
        data = pack_int4_lanes(centered)
    elif storage == "int4":
        data = centered.astype(ml_dtypes.int4)
    else:
        data = centered
    s = np.asarray(scales, np.float32)
    return GroupQTensor(
        data,
        s,
        (z.astype(np.float32) - center) * s,
        out_shape=tuple(out_shape) if out_shape is not None else (O,),
        packed=(storage == "packed4"),
    )


def group_qeinsum(eq: str, x: jnp.ndarray, w: GroupQTensor) -> jnp.ndarray:
    """einsum against a group-quantized weight, contraction grouped.

    Exact algebra (no dequantized weight ever materializes in HBM):
        y[., o] = sum_g  s[g, o] * (x[., g, :] @ data[g, :, o])
                - sum_g  zs[g, o] * sum_i x[., g, i]
    computed as a ``lax.scan`` over groups with an f32 accumulator, so
    peak memory is one [batch, O] buffer and the weight streams once at
    its packed width — half a byte per param for lane-packed int4, whose
    nibble unpack fuses into the group matmul's operand load. Decoder
    contract (asserted): the weight's contraction axis is its FIRST
    logical axis and x's LAST.

    Group-axis-sharded weights (``w.group_axis``, row-parallel wo/w_down
    under TP): the scan runs inside a ``shard_map`` over that mesh axis —
    each device scans only its LOCAL G/n groups of weight AND activation,
    then a single f32 ``psum`` combines the partial sums. Both terms of
    the algebra (the matmul part and the zero-point correction) are plain
    sums over groups, so partial-summing them per device is exact.
    """
    lhs, out_sub = eq.split("->")
    x_sub, w_sub = lhs.split(",")
    n_con = len(w_sub) - len(w.out_shape)
    assert x_sub[-n_con:] == w_sub[:n_con] and all(
        c not in out_sub for c in w_sub[:n_con]), (
        f"group_qeinsum: {eq} does not contract the weight's leading axes")
    G, O = w.data.shape[-3], w.data.shape[-1]
    gs = w.group_size
    lead = x.shape[:-n_con]
    xg = x.reshape(lead + (G, gs))
    xs_x = jnp.moveaxis(xg, -2, 0)                     # [G, ..., gs]

    def body(acc, per_g):
        xg_, qg, sg, zg = per_g                        # [..., gs] / [gs, O]
        if w.packed:
            qg = unpack_int4_lanes(qg)
        part = jnp.einsum("...i,io->...o", xg_, qg.astype(x.dtype),
                          preferred_element_type=jnp.float32)
        xsum = xg_.sum(axis=-1).astype(jnp.float32)[..., None]
        return acc + part * sg - xsum * zg, None

    def scan_groups(xs, data, scale, zero_scaled):
        acc0 = jnp.zeros(lead + (O,), jnp.float32)
        acc, _ = jax.lax.scan(body, acc0, (xs, data, scale, zero_scaled))
        return acc

    ax = w.group_axis
    mesh = None
    if ax is not None:
        from llms_on_kubernetes_tpu.parallel.mesh import get_active_mesh

        mesh = get_active_mesh()
    if mesh is not None and mesh.shape.get(ax, 1) > 1 \
            and G % mesh.shape[ax] == 0:
        from jax.sharding import PartitionSpec as P

        from llms_on_kubernetes_tpu.ops.shard_map_compat import shard_map

        def local(xs, data, scale, zero_scaled):
            return jax.lax.psum(
                scan_groups(xs, data, scale, zero_scaled), ax)

        acc = shard_map(
            local, mesh=mesh,
            in_specs=(P(ax, *(None,) * (xs_x.ndim - 1)),
                      P(ax, None, None), P(ax, None), P(ax, None)),
            out_specs=P(*(None,) * (len(lead) + 1)),
        )(xs_x, w.data, w.scale, w.zero_scaled)
    else:
        acc = scan_groups(xs_x, w.data, w.scale, w.zero_scaled)
    return acc.reshape(lead + w.out_shape).astype(x.dtype)


def qeinsum(eq: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """einsum where the second operand may be a QTensor.

    The per-output-channel scale is applied to the einsum OUTPUT, not the
    weight: scales live only on non-contracted axes (keepdims policy), so
    ``einsum(x, w_int8 * s) == einsum(x, w_int8) * s_broadcast`` exactly —
    and the multiply touches the small activation tensor instead of the
    weight, guaranteeing the dequantized weight never materializes in HBM
    no matter how XLA schedules the fusion. Only the int8->bf16 convert
    rides on the weight read (fused into the MXU operand load).
    """
    if isinstance(w, GroupQTensor):
        return group_qeinsum(eq, x, w)
    if not isinstance(w, QTensor):
        return jnp.einsum(eq, x, w)
    lhs, out = eq.split("->")
    _, w_sub = lhs.split(",")
    # scale's non-1 dims sit on w's non-contracted axes, which appear in
    # the output in the same relative order (true for every decoder eq)
    out_shape = tuple(
        w.scale.shape[w_sub.index(c)] if c in w_sub else 1 for c in out
    )
    y = jnp.einsum(eq, x, w.data.astype(x.dtype))
    return (y * w.scale.reshape(out_shape).astype(x.dtype)).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pre-quantized checkpoint formats (reference values.yaml:2-12: the default
# models are gemma-3-27b-it-FP8-Dynamic, a compressed-tensors FP8
# checkpoint, and Qwen3-VL-...-AWQ, an AWQ checkpoint). Both are
# dequantized tensor-by-tensor at load and re-quantized to the TPU-native
# serving format (weight-only int8 per-output-channel, streamed at
# 1 byte/param — same on-device footprint as the source format); accuracy
# is pinned by logit-tolerance tests against a full-precision dequant.
# ---------------------------------------------------------------------------

# AutoAWQ's nibble interleave: bits [4k, 4k+4) of a packed int32 hold the
# quantized value for output channel 8*j + _AWQ_ORDER[k].
_AWQ_ORDER = (0, 2, 4, 6, 1, 3, 5, 7)


def _awq_unpack(arr, bits: int):
    """[r, c] int32 -> [r, c*pack] unpacked values with AWQ interleave."""
    import numpy as np

    if bits not in (4, 8):
        raise ValueError(f"unsupported AWQ bits={bits}")
    pack = 32 // bits
    mask = (1 << bits) - 1
    r, c = arr.shape
    out = np.empty((r, c * pack), np.int32)
    order = _AWQ_ORDER if bits == 4 else range(pack)
    for k, o in enumerate(order):
        out[:, o::pack] = (arr >> (bits * k)) & mask
    return out


def awq_dequantize(qweight: "np.ndarray", qzeros: "np.ndarray",
                   scales: "np.ndarray", bits: int = 4) -> "np.ndarray":
    """AWQ GEMM-format dequant -> float32 [in, out].

    qweight int32 [in, out*bits/32], qzeros int32 [n_groups, out*bits/32],
    scales f16/f32 [n_groups, out]; group_size = in / n_groups;
    w[i, o] = (q[i, o] - z[g(i), o]) * s[g(i), o].
    """
    import numpy as np

    q = _awq_unpack(qweight.astype(np.int32), bits)  # [in, out]
    z = _awq_unpack(qzeros.astype(np.int32), bits)   # [n_groups, out]
    n_groups = z.shape[0]
    group = q.shape[0] // n_groups
    zf = np.repeat(z, group, axis=0).astype(np.float32)
    sf = np.repeat(scales.astype(np.float32), group, axis=0)
    return (q.astype(np.float32) - zf) * sf        # [in, out]


def fp8_dequantize(weight: "np.ndarray", weight_scale) -> "np.ndarray":
    """compressed-tensors FP8 dequant -> float32 [out, in].

    weight float8_e4m3fn [out, in]; weight_scale f32 — scalar (per-tensor)
    or [out, 1] (per-channel).
    """
    import numpy as np

    w = weight.astype(np.float32)
    s = np.asarray(weight_scale, np.float32)
    if s.ndim == 1:  # [out] -> per-channel column
        s = s[:, None]
    return w * s


# ---------------------------------------------------------------------------
# Whole-model quantization
# ---------------------------------------------------------------------------

# "fp8" / "awq" declare the CHECKPOINT's format (validated against its
# quantization_config at load); serving still streams weight-only int8.
SUPPORTED_QUANTIZATIONS = (None, "int8", "fp8", "awq")

# Weight name -> contraction (input) axes of the PER-LAYER slice, offset by
# +1 for the stacked layer axis. wq [L, D, H, hd] contracts over D -> (1,).
_LAYER_REDUCE_AXES = {
    "wq": (1,), "wk": (1,), "wv": (1,),
    "wo": (1, 2),                 # [L, H, hd, D] contracts over (H, hd)
    "w_gate": None, "w_up": None, "w_down": None,  # shape-dependent (MoE)
}


def reduce_axes_for(name: str, ndim: int) -> tuple[int, ...]:
    """Contraction axes for a stacked weight — single source of the
    quantization-axis policy (used by quantize_params and the benchmark
    param generator alike)."""
    axes = _LAYER_REDUCE_AXES[name]
    if axes is not None:
        return axes
    # mlp weights: MoE [L, E, in, out]-style contracts dim 2, dense dim 1
    return (2,) if ndim == 4 else (1,)


def quantize_params(params: Params) -> Params:
    """Quantize the big matmul weights of a decoder param tree to int8.

    Embedding / lm_head / norms stay in their original dtype (the embedding
    is a gather, not a matmul, and the final logits matmul is accuracy-
    critical — same policy as the AWQ/FP8 checkpoints the reference served,
    which keep embeddings in 16-bit).
    """
    out = dict(params)
    layers = dict(params["layers"])
    for name in _LAYER_REDUCE_AXES:
        w = layers.get(name)
        if w is None or isinstance(w, QTensor):
            continue
        layers[name] = quantize(w, reduce_axes_for(name, w.ndim))
    out["layers"] = layers
    return out



@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _pattern(shape, dtype, seed: int):
    """Cheap pseudo-random fill: fused iota -> hash -> cast, so only the
    final dtype ever materializes (an 8B model's int8 weights build in
    milliseconds with ~zero temp HBM — real RNG over a device tunnel took
    minutes and doubled peak memory)."""
    n = 1
    for s in shape:
        n *= s
    x = jax.lax.iota(jnp.uint32, n) * jnp.uint32(2654435761) + jnp.uint32(seed)
    x = (x >> 8) % 255  # [0, 255)
    if jnp.dtype(dtype) == jnp.int8:
        return (x.astype(jnp.int32) - 127).astype(jnp.int8).reshape(shape)
    return ((x.astype(jnp.float32) / 127.0 - 1.0) * 0.02).astype(dtype).reshape(shape)


def random_quantized_params(cfg, key: jax.Array) -> Params:
    """Pseudo-random already-int8 params for big-model compile checks and
    weight-streaming benchmarks (values don't matter, shapes/dtypes do).

    Never materializes a full-precision weight: matmul weights are generated
    directly as int8 (+ constant scales), so Llama-3-8B fits a single 16 GB
    v5e chip (~9 GB) — the configuration the BASELINE north star benches.
    """
    from llms_on_kubernetes_tpu.models.decoder import init_params

    del key  # deterministic pattern fill; kept for API symmetry
    shapes = jax.eval_shape(lambda k: init_params(cfg, k), jax.random.key(0))
    quant_names = set(_LAYER_REDUCE_AXES)
    seed = iter(range(1, 256))
    out: Params = {}
    for section, val in shapes.items():
        if section != "layers":
            out[section] = _pattern(val.shape, val.dtype, next(seed))
    layers: Params = {}
    for name, leaf in shapes["layers"].items():
        if name in quant_names:
            data = _pattern(leaf.shape, jnp.int8, next(seed))
            axes = reduce_axes_for(name, len(leaf.shape))
            # keep leading (layer-stack) axis and out channels in the scale
            sshape = tuple(1 if i in axes else s
                           for i, s in enumerate(leaf.shape))
            layers[name] = QTensor(data, jnp.full(sshape, 1e-3, jnp.float32))
        elif name in ("attn_norm", "mlp_norm", "q_norm", "k_norm",
                      "attn_post_norm", "mlp_post_norm", "final_norm"):
            layers[name] = jnp.ones(leaf.shape, leaf.dtype)
        else:
            layers[name] = _pattern(leaf.shape, leaf.dtype, next(seed))
    out["layers"] = layers
    return out


def scale_spec(data_spec, scale_shape) -> "jax.sharding.PartitionSpec":
    """PartitionSpec for a QTensor's scale given the data's spec: kept
    (size>1) dims inherit the data's axis, reduced (size==1) dims are
    unsharded."""
    from jax.sharding import PartitionSpec as P

    dims = list(data_spec) + [None] * (len(scale_shape) - len(data_spec))
    return P(*[a if s > 1 else None for a, s in zip(dims, scale_shape)])

"""Batched multi-adapter LoRA: per-slot low-rank deltas in one matmul pass.

Punica (Chen et al., 2023) and S-LoRA (Sheng et al., 2023) serve many
fine-tunes from one base model by keeping the base weights shared and
applying each request's low-rank delta inside the batched step. The TPU
port follows ``ops/quant.py::group_qeinsum``'s structure: a ``lax.scan``
over the resident adapter slots with an f32 accumulator, each slot's
delta masked to the batch rows that selected it (the segmented-matmul
formulation — every slot's two rank-r matmuls run over the whole batch,
which at decode batch sizes and r<=64 is noise next to the base matmul).

- ``LoRAStack`` holds EVERY resident adapter's A/B factors for one target
  weight, slot-major, with the engine's layer-stack axis leading — so the
  stacks ride ``lax.scan`` over ``params["layers"]`` and slice per layer
  like any other leaf. Empty slots are zeros: their delta vanishes, so
  slot residency never changes the compiled program.
- ``lora_qeinsum`` adds the gathered delta on top of ``qeinsum`` of the
  BASE weight — additive on the output, so it composes unchanged with
  QTensor / GroupQTensor (packed4 AWQ) bases; the base path stays the
  exact kernel the non-LoRA engine runs.
- Rank-axis sharding: when the stack's ``rank_axis`` names a mesh axis
  that divides r, the slot scan runs under ``shard_map`` with each device
  holding a rank shard of A and B; the delta is a sum over rank, so a
  single f32 ``psum`` combines the partial deltas exactly (mirrors
  group_qeinsum's group-axis sharding).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class LoRAStack:
    """All resident adapters' factors for ONE target weight.

    a   [..., S, *in_dims, r]  float32 (x @ a -> rank space)
    b   [..., S, r, *out_dims] float32 (rank space -> output; the
        adapter's alpha/r scale is folded in at upload time)
    Leading axes (the engine's layer stack) ride along and slice under
    ``lax.scan``. ``rank_axis`` is pytree AUX data — the mesh axis name
    sharding the rank dimension, or None when replicated.
    """

    def __init__(self, a, b, rank_axis=None):
        self.a = a
        self.b = b
        self.rank_axis = rank_axis

    @property
    def rank(self):
        return self.a.shape[-1]

    def tree_flatten(self):
        return (self.a, self.b), (self.rank_axis,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, rank_axis=aux[0])

    def __repr__(self):
        return (f"LoRAStack(a={tuple(self.a.shape)}, "
                f"b={tuple(self.b.shape)}, rank_axis={self.rank_axis})")


def lora_zeros(num_layers: int, num_slots: int, in_shape: tuple,
               out_shape: tuple, rank: int) -> LoRAStack:
    """An empty (all-slots-vacant) stack for one layer-stacked target."""
    a = jnp.zeros((num_layers, num_slots) + tuple(in_shape) + (rank,),
                  jnp.float32)
    b = jnp.zeros((num_layers, num_slots, rank) + tuple(out_shape),
                  jnp.float32)
    return LoRAStack(a, b)


def _delta_eqs(eq: str) -> tuple[str, str]:
    """Derive the two rank-space einsums from the base equation.

    "btd,dhk->bthk" -> ("btd,dr->btr", "btr,rhk->bthk"): contract x with
    A over the base contraction dims into rank space, then expand with B
    into the base output dims. Works for every decoder equation because
    the weight's contracted dims are exactly x's dims shared with w.
    """
    lhs, out = eq.split("->")
    x_sub, w_sub = lhs.split(",")
    batch = "".join(c for c in x_sub if c not in w_sub)
    contract = "".join(c for c in x_sub if c in w_sub)
    out_dims = "".join(c for c in out if c not in x_sub)
    assert "r" not in eq, f"rank label collides in {eq!r}"
    return (f"{x_sub},{contract}r->{batch}r",
            f"{batch}r,r{out_dims}->{out}")


def lora_delta(eq: str, x: jnp.ndarray, lora: LoRAStack,
               idx: jnp.ndarray) -> jnp.ndarray:
    """Sum of per-slot adapter deltas, each masked to its batch rows.

    ``idx`` [B] int32 holds each row's adapter slot (< 0 = base model,
    matches no slot). Returns the f32 delta with the base output's shape.
    """
    a, b = lora.a, lora.b  # [S, *in, r] / [S, r, *out] (layer axis sliced)
    S = a.shape[0]
    eq_a, eq_b = _delta_eqs(eq)

    def scan_slots(xf, idx_, a_, b_):
        def body(acc, per_s):
            a_s, b_s, s = per_s
            t = jnp.einsum(eq_a, xf, a_s,
                           preferred_element_type=jnp.float32)
            d = jnp.einsum(eq_b, t, b_s,
                           preferred_element_type=jnp.float32)
            keep = (idx_ == s).reshape((-1,) + (1,) * (d.ndim - 1))
            return acc + jnp.where(keep, d, 0.0), None

        # delta shape: out labels resolve against x (batch/contract dims)
        # or against b's trailing out dims ([S, r, *out_dims])
        lhs, out = eq.split("->")
        x_sub = lhs.split(",")[0]
        out_dims = [c for c in out if c not in x_sub]
        shape = tuple(xf.shape[x_sub.index(c)] if c in x_sub
                      else b_.shape[2 + out_dims.index(c)] for c in out)
        acc0 = jnp.zeros(shape, jnp.float32)
        acc, _ = jax.lax.scan(
            body, acc0, (a_, b_, jnp.arange(S, dtype=jnp.int32)))
        return acc

    xf = x.astype(jnp.float32)
    ax = lora.rank_axis
    mesh = None
    if ax is not None:
        from llms_on_kubernetes_tpu.parallel.mesh import get_active_mesh

        mesh = get_active_mesh()
    if mesh is not None and mesh.shape.get(ax, 1) > 1 \
            and lora.rank % mesh.shape[ax] == 0:
        from jax.sharding import PartitionSpec as P

        from llms_on_kubernetes_tpu.ops.shard_map_compat import shard_map

        def local(xf_, idx_, a_, b_):
            # each device scans its rank shard; delta is a sum over rank
            return jax.lax.psum(scan_slots(xf_, idx_, a_, b_), ax)

        out_ndim = len(eq.split("->")[1])
        return shard_map(
            local, mesh=mesh,
            in_specs=(P(*([None] * xf.ndim)), P(None),
                      P(*([None] * (a.ndim - 1) + [ax])),
                      P(None, ax, *([None] * (b.ndim - 2)))),
            out_specs=P(*([None] * out_ndim)),
        )(xf, idx, a, b)
    return scan_slots(xf, idx, a, b)


def lora_qeinsum(eq: str, x: jnp.ndarray, w, lora, idx) -> jnp.ndarray:
    """``qeinsum`` plus the batch's per-row adapter deltas.

    ``lora`` None or ``idx`` None short-circuits to the exact base kernel
    (adapter-free engines trace the identical program they always did).
    """
    from llms_on_kubernetes_tpu.ops.quant import qeinsum

    base = qeinsum(eq, x, w)
    if lora is None or idx is None:
        return base
    return (base.astype(jnp.float32)
            + lora_delta(eq, x, lora, idx)).astype(base.dtype)


def merge_delta(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Dense [in.., out..] weight delta for ONE adapter's (a, b) factors
    ([in.., r], [r, out..]) — the merged-weights reference the parity
    tests check the batched path against."""
    return jnp.tensordot(jnp.asarray(a, jnp.float32),
                         jnp.asarray(b, jnp.float32), axes=[[-1], [0]])

"""shard_map across JAX versions, resolved once at import.

Two things moved under us: the import location (jax.experimental.shard_map
-> top-level jax.shard_map) and the replication-check kwarg
(check_rep -> check_vma). Passing the wrong spelling is a TypeError at
trace time, which took down every CP/ring code path on jax 0.4.x
(observed: dryrun_multichip's CP layout and tests/test_ring.py). All
in-repo callers go through this wrapper instead of importing shard_map
directly.
"""

import inspect

try:
    from jax import shard_map as _shard_map  # new import location
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

_CHECK_KW = ("check_vma"
             if "check_vma" in inspect.signature(_shard_map).parameters
             else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
    """shard_map with the replication/VMA check spelled for the running
    JAX version. ``check=False`` everywhere in this repo: the specs are
    exact by construction and the check re-traces."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check})

"""Deterministic fault injection for the serving spine (``LLMK_FAULT=``).

Every fault-tolerance path in this repo — entry-point timeouts, router
retries/breakers, the engine watchdog — is testable on CPU by flipping an
environment variable instead of waiting for real infrastructure to break.
The hooks are read from the environment *at call time*, so tests can
monkeypatch ``LLMK_FAULT`` (or pass it to a subprocess) without import-order
games, and production pays one ``os.environ.get`` per hook site.

Spec grammar::

    LLMK_FAULT="<name>[:<arg>][;<name>[:<arg>]...]"

Known fault names (each documented at its injection site):

- ``backend_hang``        — accelerator-backend initialization never
  returns (simulates a wedged TPU runtime).  Injected immediately before
  the first backend touch in ``bench.py``'s probe subprocess; the parent's
  hard timeout must convert it into a clean JSON error.
- ``engine_stall[:N]``    — the harvester never observes completion of the
  N-th (default: first) device step, simulating a hung device program.
  The engine watchdog must detect it and shed in-flight work.
- ``slow_step[:SECONDS]`` — every device-step completion is delayed by
  SECONDS (default 0.2), for pacing/timeout tests that need a slow but
  live device.
- ``queue_stall``         — the engine's admission loop refuses to admit
  while the flag is set: waiting requests age in the queue without ever
  being prefilled. Drives the deadline queue-shed path and the
  queue-depth-based 429 ``Retry-After`` estimate deterministically.
- ``flappy_replica[:PERIOD]`` — the server's readiness flaps: ``/ready``
  alternates between ``serving`` and 503 ``draining`` every PERIOD
  seconds (default 1.0) while the engine keeps serving. A cluster-level
  fault (replica joining/leaving endpoints repeatedly) for exercising
  router health-probe ejection/re-admission against a live server.
- ``slow_cold_start[:SECONDS]`` — server startup holds the replica in
  ``loading`` (readiness 503) for SECONDS (default 2.0) before serving:
  a compile-cache-miss cold start in miniature, so spike/scale-out tests
  see a realistically slow replica join.
- ``kill_mid_stream[:N_TOKENS]`` — the first in-process stream to deliver
  N_TOKENS (default 8) tokens severs its client socket abruptly (TCP
  RST), simulating a replica dying mid-generation. One-shot per process
  via :func:`claim` (like ``preempt_replica``): with several in-process
  replicas behind one router, exactly ONE stream is killed — the point is
  proving the router's journal resume splices the continuation from a
  surviving replica with zero client-visible drops.
- ``preempt_replica[:DELAY]`` — DELAY seconds (default 1.0) after a
  server starts serving, it receives a simulated spot-TPU preemption
  notice and begins the graceful drain (readiness 503, in-flight streams
  finish, no new admissions). One-shot per process via :func:`claim`:
  with several in-process replicas sharing the env (tests, bench),
  exactly ONE is preempted — the point is proving the survivors absorb
  its traffic with zero dropped streams.
- ``overload_spike[:LEVEL]`` — the Python router's QoS gate treats the
  gateway as already at brownout level LEVEL (default 2, clamped 0..3)
  regardless of the real queue-depth/burn-rate signals, so the
  shed-lowest-priority-first ladder is testable without generating real
  overload. See ``server/qos.py`` for the level -> action table.
- ``kill_prefill_replica[:DELAY]`` — DELAY seconds (default 1.0) after a
  ``prefill``-role server starts serving, it dies abruptly: readiness
  goes 503 AND in-flight/new prefill requests are refused (no graceful
  drain — a prefill pod crash, not a preemption notice). One-shot per
  process via :func:`claim`, and only prefill-role servers arm it: with
  a disaggregated fleet sharing one env, exactly ONE prefill replica is
  killed — the point is proving the router retries surviving prefill
  replicas or falls back to colocated serving with zero dropped streams.
- ``degraded_replica[:FACTOR]`` — the canonical GRAY failure: one
  server's streams decode at 1/FACTOR speed (default 8; inter-event
  pacing stretched in the delivery path) while ``/health`` and
  ``/ready`` keep answering green, so probe-based ejection never fires.
  One-shot per process via :func:`claim`: with several in-process
  replicas sharing the env, exactly ONE degrades — the point is proving
  the router's latency outlier detector quarantines it from in-band
  TTFT alone (server/outlier.py, ISSUE 17's chaos_bench).
- ``net_jitter[:MS]`` — every stream event on EVERY replica sharing the
  env is delayed by a uniform random 0..MS ms (default 25): benign
  network/scheduler latency noise. The outlier detector's cv/spread
  floors must absorb this without ejecting anyone (the false-positive
  half of the gray-failure story).
- ``drop_handoff[:N]`` — the first N (default 1) KV-handoff ingests on a
  ``decode``-role server pretend every handed-off page is missing (the
  pull is skipped entirely), forcing the counted full-re-prefill
  degraded path. Claimed per-ingest via :func:`claim_n` so N spans the
  whole process, however many decode replicas share it — the point is
  proving a dropped handoff is never a client-visible error.

Routers do not read ``LLMK_FAULT``, with one documented exception:
``overload_spike`` above, a brownout-ladder hook for the Python router
only (the native router's overload behavior is exercised through real
config-driven thresholds). All other router faults (connection resets,
stalled responses) are injected by the fake upstream backends in the test
fixtures, which is both more deterministic and closer to the real failure.
"""

from __future__ import annotations

import os
import threading
import time

ENV_VAR = "LLMK_FAULT"


def _parse(raw: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, arg = part.partition(":")
        out[name.strip()] = arg.strip()
    return out


def get(name: str) -> str | None:
    """Arg string of fault ``name`` ("" if given bare), or None if inactive."""
    return _parse(os.environ.get(ENV_VAR, "")).get(name)


def is_active(name: str) -> bool:
    return get(name) is not None


def get_float(name: str, default: float) -> float | None:
    """Float arg of fault ``name`` (``default`` if bare); None if inactive."""
    arg = get(name)
    if arg is None:
        return None
    try:
        return float(arg) if arg else default
    except ValueError:
        return default


def inject_hang(name: str, hang_s: float = 3600.0) -> None:
    """If fault ``name`` is active, sleep far past any caller's deadline.

    The caller is expected to wrap the hanging code path in a hard timeout
    (subprocess timeout, watchdog) — the injected hang proves that timeout
    actually fires.  Sleeps in 1 s slices, re-checking the env each slice,
    so signals still interrupt and an in-process test's monkeypatch
    teardown releases a hung background thread instead of stranding it.
    """
    if not is_active(name):
        return
    deadline = time.monotonic() + hang_s
    while time.monotonic() < deadline and is_active(name):
        time.sleep(1.0)


def inject_delay(name: str, default_s: float) -> None:
    """If fault ``name`` is active, sleep its arg (or ``default_s``)."""
    s = get_float(name, default_s)
    if s is not None and s > 0:
        time.sleep(s)


# one-shot faults: first in-process claimer wins (see preempt_replica)
_claimed: set[str] = set()
_claim_counts: dict[str, int] = {}
_claim_lock = threading.Lock()


def claim(name: str) -> bool:
    """True exactly once per process for an active fault ``name``.

    Lets N in-process replicas share one ``LLMK_FAULT`` env while only
    the first to reach the hook acts on it — a single-victim fault.
    """
    if not is_active(name):
        return False
    with _claim_lock:
        if name in _claimed:
            return False
        _claimed.add(name)
        return True


def claim_n(name: str, default_n: float = 1.0) -> bool:
    """True for the first N claims of an active fault ``name``, where N
    is the fault's arg (``default_n`` if bare). The N-shot sibling of
    :func:`claim` — ``drop_handoff:3`` drops exactly three handoffs
    process-wide, however many in-process replicas share the env."""
    n = get_float(name, default_n)
    if n is None:
        return False
    with _claim_lock:
        used = _claim_counts.get(name, 0)
        if used >= int(n):
            return False
        _claim_counts[name] = used + 1
        return True


def reset_claims() -> None:
    """Forget one-shot claims (test isolation between cases)."""
    with _claim_lock:
        _claimed.clear()
        _claim_counts.clear()

"""TPU-native multi-model LLM serving framework for Kubernetes.

A ground-up rebuild of the capabilities of `graz-dev/llms-on-kubernetes`
(a GitOps multi-model LLM serving stack; see /root/reference) designed
TPU-first:

- serving engine: pure-JAX models sharded with ``jax.sharding`` over a
  ``Mesh`` (axes ``data``/``expert``/``model``), Pallas paged-attention and
  flash-attention kernels, paged KV cache, continuous batching under XLA's
  static-shape regime (reference delegated all of this to the pulled
  ``vllm/vllm-openai`` CUDA image — reference
  vllm-models/helm-chart/templates/model-deployments.yaml:21).
- serving API: OpenAI-compatible HTTP server with SSE streaming
  (reference: in-image vLLM server).
- router: payload-inspecting multi-model gateway, Python (asyncio,
  streaming) and native C++ implementations (reference: OpenResty/Lua —
  vllm-models/helm-chart/templates/model-gateway.yaml).
- packaging: the same declarative ``models[]`` contract rendered into
  Kubernetes manifests, but scheduling onto GKE TPU node pools
  (``google.com/tpu``) instead of ``nvidia.com/gpu``.
"""

__version__ = "0.1.0"

"""Per-tenant deficit-weighted fair queuing for engine admission (ISSUE 10).

The admission queue used to be a plain FIFO deque: one tenant submitting a
burst of requests parked every other tenant behind it until the burst
drained — the classic noisy-neighbor failure mode. ``TenantFairQueue``
replaces it with deficit round-robin (DRR) across tenants inside strict
priority classes:

- **Priority classes** (``interactive`` > ``normal`` > ``batch``): the head
  is always drawn from the most important non-empty class, EXCEPT that a
  lower class whose oldest request has waited longer than ``starvation_s``
  preempts the scan (anti-starvation aging — batch work always makes
  progress, just slowly).
- **DRR within a class**: each tenant accumulates ``weight`` deficit per
  round-robin turn and spends 1 per admitted request, so over a backlog
  tenants are served in proportion to their weights regardless of how
  deep any one tenant's queue is. A tenant's deficit resets when its queue
  empties — an idle tenant cannot bank credit.

The queue is API-compatible with the subset of ``collections.deque`` the
engine scheduler uses — ``[0]`` peek, ``popleft``, ``append``,
``appendleft``, ``remove``, ``clear``, iteration, ``len()`` — with one
deliberate strengthening: the ``[0]`` peek is **sticky**. Once a head is
chosen it stays the head until it is actually popped or removed
(``appendleft`` — the preemption requeue — takes the head over, matching
deque semantics). The scheduler peeks the head, tentatively pins resources
(grammar rows, adapter slots) against it, and may bail out without popping;
a head that silently changed between peeks would leak those pins against a
request that is no longer next, and in the worst case livelock admission
behind a tenant whose head can never acquire its pinned resource.

Thread-safety: like the deque it replaces, the queue relies on the
engine's external ``_lock`` for compound operations; individual methods
only touch plain Python structures.
"""

from __future__ import annotations

import collections
import time
from typing import Iterator, Optional

# Most- to least-important. Unknown strings normalize to "normal".
PRIORITIES = ("interactive", "normal", "batch")
_PRIORITY_RANK = {"interactive": 0, "normal": 1, "batch": 2}

# Floor for configured weights: a zero/negative weight would make DRR spin
# forever accumulating deficit for a tenant that never crosses 1.
MIN_WEIGHT = 0.01


def priority_rank(priority: Optional[str]) -> int:
    """0 = interactive, 1 = normal, 2 = batch; unknown/None -> normal."""
    return _PRIORITY_RANK.get(priority or "", 1)


def normalize_priority(priority: Optional[str],
                       default: str = "normal") -> str:
    """Clamp an arbitrary string to a known priority class."""
    p = (priority or "").strip().lower()
    return p if p in _PRIORITY_RANK else (
        default if default in _PRIORITY_RANK else "normal")


class TenantFairQueue:
    """Deque-compatible admission queue: priority classes + per-tenant DRR.

    ``weights`` maps tenant name -> relative weight (default
    ``default_weight`` for unlisted tenants). ``starvation_s`` is the
    anti-starvation aging threshold; <= 0 disables aging (strict priority).
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, weights: Optional[dict] = None,
                 default_weight: float = 1.0,
                 starvation_s: float = 5.0,
                 clock=time.monotonic):
        self._weights = {str(k): max(MIN_WEIGHT, float(v))
                         for k, v in (weights or {}).items()}
        self._default_weight = max(MIN_WEIGHT, float(default_weight))
        self.starvation_s = float(starvation_s)
        self._clock = clock
        # preemption requeues jump every class/tenant: absolute head
        self._front: collections.deque = collections.deque()
        # per-rank DRR state: rank -> tenant -> deque[Request]
        self._queues: list[dict[str, collections.deque]] = [{}, {}, {}]
        self._deficit: list[dict[str, float]] = [{}, {}, {}]
        self._order: list[list[str]] = [[], [], []]  # round-robin order
        self._idx: list[int] = [0, 0, 0]             # current RR position
        self._len = 0
        # sticky head: (request, plan) — plan replays the DRR bookkeeping
        # peek() computed, applied only when the head is actually popped
        self._pick = None
        self._pick_plan = None

    # -- deque-compatible surface --------------------------------------

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def __iter__(self) -> Iterator:
        yield from self._front
        for rank in range(3):
            for tenant in self._order[rank]:
                yield from self._queues[rank].get(tenant, ())

    def __getitem__(self, i: int):
        if i != 0:
            raise IndexError(
                "TenantFairQueue supports only head ([0]) indexing")
        head = self.peek()
        if head is None:
            raise IndexError("peek from an empty queue")
        return head

    def append(self, req) -> None:
        tenant, rank = self._key(req)
        q = self._queues[rank].get(tenant)
        if q is None:
            q = self._queues[rank][tenant] = collections.deque()
            self._order[rank].append(tenant)
            self._deficit[rank].setdefault(tenant, 0.0)
        q.append(req)
        self._len += 1

    def appendleft(self, req) -> None:
        """Absolute-front insert (the preemption requeue): the victim must
        re-admit before anything else, whatever its tenant or class."""
        self._front.appendleft(req)
        self._len += 1
        self._pick, self._pick_plan = req, ("front", None, None)

    def popleft(self):
        head = self.peek()
        if head is None:
            raise IndexError("pop from an empty queue")
        kind, rank, tenant = self._pick_plan
        if kind == "front":
            self._front.popleft()
        else:
            q = self._queues[rank][tenant]
            q.popleft()
            if kind == "drr":
                # commit the refills peek() simulated, then spend 1
                self._apply_refills()
                self._deficit[rank][tenant] -= 1.0
            if not q:
                self._drop_tenant(rank, tenant)
            elif kind == "drr" and self._deficit[rank][tenant] < 1.0:
                # deficit exhausted: this tenant's turn is over — advance
                # the RR pointer or the next peek would refill it straight
                # away and it would monopolize the class
                self._idx[rank] = (self._idx[rank] + 1) % len(
                    self._order[rank])
        self._len -= 1
        self._pick = self._pick_plan = None
        return head

    def remove(self, req) -> None:
        if req is self._pick:
            self._pick = self._pick_plan = None
        try:
            self._front.remove(req)
            self._len -= 1
            return
        except ValueError:
            pass
        tenant, rank = self._key(req)
        q = self._queues[rank].get(tenant)
        if q is None:
            raise ValueError("request not in queue")
        q.remove(req)  # raises ValueError if absent
        self._len -= 1
        if not q:
            self._drop_tenant(rank, tenant)

    def clear(self) -> None:
        self._front.clear()
        self._queues = [{}, {}, {}]
        self._deficit = [{}, {}, {}]
        self._order = [[], [], []]
        self._idx = [0, 0, 0]
        self._len = 0
        self._pick = self._pick_plan = None

    # -- scheduling ----------------------------------------------------

    def peek(self):
        """The next request to admit. Sticky: repeated peeks return the
        same request until it is popped/removed (or an appendleft takes
        the head over)."""
        if self._pick is not None:
            return self._pick
        if self._front:
            self._pick, self._pick_plan = self._front[0], ("front", None, None)
            return self._pick
        if self._len == 0:
            return None
        base_rank = next(r for r in range(3) if self._queues[r])
        starved = self._starved_below(base_rank)
        if starved is not None:
            rank, tenant = starved
            self._pick = self._queues[rank][tenant][0]
            self._pick_plan = ("aged", rank, tenant)
            return self._pick
        tenant = self._drr_select(base_rank)
        self._pick = self._queues[base_rank][tenant][0]
        self._pick_plan = ("drr", base_rank, tenant)
        return self._pick

    def _starved_below(self, base_rank: int):
        """(rank, tenant) of the most-starved head in a class BELOW
        base_rank, or None. Starved = head wait > starvation_s."""
        if self.starvation_s <= 0:
            return None
        now = self._clock()
        worst, worst_wait = None, self.starvation_s
        for rank in range(base_rank + 1, 3):
            for tenant, q in self._queues[rank].items():
                wait = now - getattr(q[0], "submitted_at", now)
                if wait > worst_wait:
                    worst, worst_wait = (rank, tenant), wait
        return worst

    def _drr_select(self, rank: int) -> str:
        """Pick the tenant to serve within ``rank`` WITHOUT mutating the
        DRR state; the refills simulated here are stashed and committed by
        popleft() (peek must stay pure — the scheduler peeks repeatedly
        while deciding whether it can admit at all)."""
        order, idx = self._order[rank], self._idx[rank]
        deficit = self._deficit[rank]
        self._pending_refills: list[tuple[int, str, float]] = []
        n = len(order)
        sim = {}
        for _visit in range(n * 101):  # ceil(1/MIN_WEIGHT)+1 turns worst case
            tenant = order[idx % n]
            d = sim.get(tenant, deficit[tenant])
            if d >= 1.0:
                self._idx[rank] = idx % n
                return tenant
            # turn entry: refill by weight, then re-check
            w = self._weights.get(tenant, self._default_weight)
            sim[tenant] = d + w
            self._pending_refills.append((rank, tenant, w))
            if sim[tenant] >= 1.0:
                self._idx[rank] = idx % n
                return tenant
            idx += 1
        # unreachable with weights floored at MIN_WEIGHT; serve RR head
        self._idx[rank] = idx % n
        return order[idx % n]

    def _apply_refills(self) -> None:
        for rank, tenant, w in getattr(self, "_pending_refills", ()):
            if tenant in self._deficit[rank]:
                self._deficit[rank][tenant] += w
        self._pending_refills = []

    # -- internals -----------------------------------------------------

    def _key(self, req) -> tuple[str, int]:
        tenant = str(getattr(req, "tenant", "") or "")
        rank = priority_rank(getattr(req, "priority", None))
        return tenant, rank

    def _drop_tenant(self, rank: int, tenant: str) -> None:
        """A tenant's queue emptied: forget its DRR state (deficit resets
        — credit must not be bankable across idle periods) and its RR
        slot, keeping the RR index pointed at the next survivor."""
        del self._queues[rank][tenant]
        self._deficit[rank].pop(tenant, None)
        order = self._order[rank]
        pos = order.index(tenant)
        order.pop(pos)
        if pos < self._idx[rank]:
            self._idx[rank] -= 1
        if order:
            self._idx[rank] %= len(order)
        else:
            self._idx[rank] = 0

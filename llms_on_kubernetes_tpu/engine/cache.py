"""Paged KV cache: device-side page pool + host-side page allocator.

The reference stack got paged attention from the vLLM image (reference
SURVEY §2.3); this is the TPU-native equivalent. Design:

- ONE flat pool for all layers: ``k_pages``/``v_pages`` have shape
  [n_kv, L * P, page_size, head_dim] — **head-major**, so one
  (head, page) slice is a contiguous [page, d] block: the Pallas decode
  kernel DMAs it HBM→VMEM in a single aligned transfer (a head-minor
  layout puts n_kv in the tiled sublane slot and Mosaic rejects the
  size-1 slice). Layer ``l``'s pages occupy the block [l*P, (l+1)*P); the
  decoder adds ``l*P`` to the (per-layer-local) page table inside the
  layer body. n_kv is the sharded axis (mesh "model") so each TP shard
  holds its own heads' pages — the pool never crosses chips.

  Why flat instead of a leading [L, ...] axis: the layer loop is
  ``lax.scan``, and a pool that rides the scan as xs/ys gets its updated
  per-layer slices STACKED into a fresh output buffer — a full pool
  rewrite (GBs) every step. The flat pool rides the scan CARRY, where
  XLA aliases the buffer across iterations and the per-token scatter
  lowers to a true in-place update (measured: the xs/ys layout cost
  ~19 ms/step at Llama-3-8B scale; the carry layout ~0).
- Physical page ``l*P`` (per-layer-local page 0) is reserved as a trash
  page: padded prompt positions write there, so prefill needs no masking
  on the scatter path. It is never allocated and never read (length masks
  exclude it).
- The allocator is plain host Python (free list) handing out PER-LAYER-
  LOCAL ids in [1, P) — every layer uses the same local table, so the
  engine ships one small [slots, pages_per_seq] int32 table per step.

All shapes are static: ``num_pages``, ``page_size``, ``pages_per_slot`` are
fixed at engine start, which is what keeps the decode step at exactly one
compiled executable (XLA retraces on any shape change).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class CacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    num_pages: int = 2048
    page_size: int = 64
    pages_per_slot: int = 32
    dtype: str = "bfloat16"
    # "int8": per-token symmetric KV quantization (scale per (head, page,
    # token) stored beside the data) — halves decode-attention HBM traffic
    # and doubles token capacity per chip. None => KV stored in `dtype`.
    kv_dtype: "Optional[str]" = None

    @property
    def max_seq_len(self) -> int:
        return self.pages_per_slot * self.page_size

    @property
    def bytes_per_page(self) -> int:
        if self.kv_dtype == "int8":
            per_tok = self.num_kv_heads * (self.head_dim + 4)  # data + scale
        else:
            per_tok = (self.num_kv_heads * self.head_dim
                       * jnp.dtype(self.dtype).itemsize)
        return 2 * self.num_layers * self.page_size * per_tok

    @property
    def bytes_per_token(self) -> int:
        """KV bytes per cached token across all layers, both sides — the
        capacity-planning number behind llm_kv_bytes_per_token. int8 is
        (head_dim + 4) bytes per (head, token, side) vs 2 * head_dim for
        bf16: ~2x smaller at head_dim 128."""
        return self.bytes_per_page // self.page_size


@jax.tree_util.register_pytree_node_class
class KVPool:
    """One side (K or V) of the paged cache: flat head-major ``data``
    [n_kv, L*P, page, d] plus, when int8-quantized, a per-token ``scale``
    [n_kv, L*P, page] float32. A pytree, so it rides jit arguments,
    donation, lax.scan carries, and device_put shardings like the plain
    array it replaces."""

    def __init__(self, data: jnp.ndarray, scale: Optional[jnp.ndarray] = None):
        self.data = data
        self.scale = scale

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def quantized(self) -> bool:
        return self.scale is not None

    def tree_flatten(self):
        if self.scale is None:
            return (self.data,), False
        return (self.data, self.scale), True

    @classmethod
    def tree_unflatten(cls, has_scale, children):
        return cls(*children) if has_scale else cls(children[0])

    def __repr__(self):
        return (f"KVPool(shape={tuple(self.data.shape)}, "
                f"dtype={self.data.dtype}, quantized={self.quantized})")


def init_pages(cfg: CacheConfig) -> tuple[KVPool, KVPool]:
    """Flat head-major pools [n_kv, L * P, page, d] (layer l's block starts
    at l * P; see module docstring for why the layer axis is folded in)."""
    shape = (cfg.num_kv_heads, cfg.num_layers * cfg.num_pages,
             cfg.page_size, cfg.head_dim)
    if cfg.kv_dtype == "int8":
        def one():
            return KVPool(jnp.zeros(shape, jnp.int8),
                          jnp.zeros(shape[:3], jnp.float32))
        return one(), one()
    if cfg.kv_dtype is not None:
        raise ValueError(f"unsupported kv_dtype {cfg.kv_dtype!r} "
                         f"(None or 'int8')")
    dt = jnp.dtype(cfg.dtype)
    return KVPool(jnp.zeros(shape, dt)), KVPool(jnp.zeros(shape, dt))


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token symmetric int8: x [..., d] -> (int8 data, f32 scale [...])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    data = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return data, scale


# Page updates are unrolled per (slot, touched page); beyond this many
# touched pages per row the code falls back to one HLO scatter. The
# threshold covers every realistic bucket/page combination (2048-token
# chunks at page 64, 1024 at 32); beyond it each LAYER's scatter copies
# the whole flat pool — only acceptable for exotic configs (huge buckets
# with tiny pages), never for the decode hot path.
_MAX_RMW_PAGES = 33


# Decode (T==1) write strategy. Part of the engine's STATIC config
# (EngineConfig.kv_write, env LLMK_KV_WRITE as the default): the value is
# baked into each engine's traced executables, so it is set via
# set_kv_write_strategy() right before every dispatch block (the
# set_active_mesh pattern) rather than read from the environment at trace
# time — two engines in one process may differ, and mutating the env var
# mid-process is no longer silently ignored (it was never re-read; now it
# is explicitly documented as resolved once at EngineConfig construction).
#
# "fused": the decode write folds INTO the Pallas attention kernel
# (ops/attention.dispatch_paged_attention_write) — no separate write op
# at all; int8 KV pools route to the quantize-at-write twin kernel
# (pool bytes match this module's quantize_kv bit-for-bit); falls back
# to "dus" behavior wherever the fused kernels don't apply (CP meshes,
# traced windows, small head_dim, int8 with page_size % 128 on real
# TPU). Opt-in
# (LLMK_KV_WRITE=fused) until validated on hardware: the kernel is only
# interpreter-tested on CPU, and a silent KV corruption is the worst
# failure mode a serving engine can ship as a default.
KV_WRITE_STRATEGIES = ("fused", "dus", "scatter", "scatter-linear")
_active_kv_write = "dus"


def set_kv_write_strategy(strategy: str) -> None:
    global _active_kv_write
    if strategy not in KV_WRITE_STRATEGIES:
        raise ValueError(f"kv_write must be one of {KV_WRITE_STRATEGIES}, "
                         f"got {strategy!r}")
    _active_kv_write = strategy


def kv_write_strategy() -> str:
    return _active_kv_write


def default_kv_write_strategy() -> str:
    """Resolve the env default ONCE (EngineConfig construction time)."""
    import os

    s = os.environ.get("LLMK_KV_WRITE", "dus")
    # legacy spelling: LLMK_KV_WRITE=scatter + LLMK_SCATTER_VARIANT=linear
    if s == "scatter" and os.environ.get("LLMK_SCATTER_VARIANT") == "linear":
        s = "scatter-linear"
    return s if s in KV_WRITE_STRATEGIES else "dus"


def _scatter_decode_writes() -> bool:
    """Decode (T==1) write strategy (see set_kv_write_strategy).

    The per-slot DUS loop costs ~0.7 us PER OP in dispatch overhead
    (profiled round 4: 4096 ops = 3.0 ms of a 23 ms Llama-3-8B step at
    B=64). One scatter per (layer, side) cuts the op count 64x and is
    MOSTLY in place — but XLA's TPU scatter lowering reserves one
    ~0.37-pool-sized HBM temp (measured 786 MB for the 2.15 GB bench
    pool; identical for 2-D and linearized index forms), which pushes the
    Llama-3-8B@16GB-v5e bench config 786 MB past HBM at COMPILE time. So
    DUS stays the default; scatter is the right choice whenever the
    deployment has that much HBM headroom (smaller models, v5p, larger
    slices)."""
    return _active_kv_write in ("scatter", "scatter-linear")


def _write_decode_scatter(kd, vd, ksc, vsc, k, v, ks, vs, pid, off, pos,
                          owner, dt):
    """One scatter per side for the whole decode batch.

    Indices are UNIQUE by construction (each active slot appends into its
    own page; rows to drop get pid = pool_size + row, distinct and out of
    range so mode="drop" discards them without breaking the uniqueness
    promise)."""
    B = pid.shape[0]
    total = kd.shape[1]
    oob = total + jnp.arange(B, dtype=pid.dtype)
    drop = pos < 0
    if owner is not None:
        base, width = owner
        lpid = pid - base
        drop = drop | (lpid < 0) | (lpid >= width)
        pid = lpid
    pid = jnp.where(drop, oob, pid)
    kh = jnp.moveaxis(k[:, 0].astype(dt), 1, 0)        # [n_kv, B, d]
    vh = jnp.moveaxis(v[:, 0].astype(dt), 1, 0)
    if _active_kv_write == "scatter-linear":
        # single-dim scatter on a [n_kv, flat*page, d] view: one index
        # vector, simplest possible lowering
        page = kd.shape[2]
        lin = pid * page + off
        n_kv, total_p, _, d = kd.shape
        kd = kd.reshape(n_kv, total_p * page, d).at[:, lin].set(
            kh, unique_indices=True, mode="drop").reshape(kd.shape)
        vd = vd.reshape(n_kv, total_p * page, d).at[:, lin].set(
            vh, unique_indices=True, mode="drop").reshape(vd.shape)
    else:
        kd = kd.at[:, pid, off].set(kh, unique_indices=True, mode="drop")
        vd = vd.at[:, pid, off].set(vh, unique_indices=True, mode="drop")
    if ks is not None:
        ksc = ksc.at[:, pid, off].set(ks[:, 0].T, unique_indices=True,
                                      mode="drop")
        vsc = vsc.at[:, pid, off].set(vs[:, 0].T, unique_indices=True,
                                      mode="drop")
    return KVPool(kd, ksc), KVPool(vd, vsc)


def write_tokens(
    k_pages: "KVPool",
    v_pages: "KVPool",
    k: jnp.ndarray,
    v: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    owner: "Optional[tuple]" = None,
) -> tuple["KVPool", "KVPool"]:
    """Write new KV for one layer into the page pool IN PLACE.

    k_pages/v_pages: KVPool — data [n_kv, P_total, page, d] (flat
                     head-major pool) + optional per-token int8 scale
    k, v:            [B, T, n_kv, d]
    page_table:      [B, pages_per_seq] int32 — GLOBAL page ids (the layer
                     body has already added its l*P block offset)
    positions:       [B, T] int32 token positions; each row's valid entries
                     are CONTIGUOUS (pos0, pos0+1, ...); negative => skip
                     (padding). Row-contiguity holds for every caller:
                     decode writes one token, prefill/chunk write a
                     front-packed chunk.

    Quantized pools write int8 data + per-token scale with the same DUS
    pattern (the quantization is per token, so an append never has to
    rescale previously written tokens).

    Implementation note (measured on v5e): HLO scatter never updates a
    multi-GB pool in place — it materializes a full copy per call — and a
    pool riding a lax.scan/while carry pays a boundary copy too. So this
    uses ``dynamic_update_slice`` exclusively (verified in-place under
    donation): one [n_kv, 1, 1, d] DUS per slot for decode (T==1), and a
    read-merge-write of each touched page for chunked writes. Callers must
    keep the layer loop UNROLLED (see decoder._run_layers) so no while
    loop ever carries the pool.

    ``owner`` = (base, width): context-parallel mode (ops/cp.py) — the
    pool argument is ONE device's shard of the flat axis, covering global
    flat slots [base, base+width); page ids are translated to local and
    non-owned updates become read-merge no-ops (a blind DUS at a clamped
    local slot would corrupt a page another sequence owns there).
    """
    B, T, n_kv, d = k.shape
    page = k_pages.shape[2]
    pps = page_table.shape[1]
    quant = k_pages.quantized
    if quant:
        kq, ks = quantize_kv(k)   # [B, T, n_kv] scale
        vq, vs = quantize_kv(v)
        k, v = kq, vq
        dt = jnp.int8
    else:
        ks = vs = None
        dt = k_pages.dtype
    kd, vd = k_pages.data, v_pages.data
    ksc, vsc = k_pages.scale, v_pages.scale

    def rewrap():
        return (KVPool(kd, ksc), KVPool(vd, vsc))

    if T == 1:
        pos = positions[:, 0]
        safe = jnp.maximum(pos, 0)
        logical = safe // page
        pid = jnp.take_along_axis(page_table, logical[:, None], axis=1)[:, 0]
        # padding -> trash page 0 (never read; keeps the write unconditional)
        pid = jnp.where(pos < 0, 0, pid)
        off = jnp.where(pos < 0, 0, safe % page)
        if _scatter_decode_writes():
            return _write_decode_scatter(
                kd, vd, ksc, vsc, k, v, ks, vs, pid, off, pos, owner, dt)
        owned = None
        if owner is not None:
            base, width = owner
            lpid = pid - base
            owned = (lpid >= 0) & (lpid < width)
            pid = jnp.where(owned, lpid, 0)
        # NOTE(measured, round 3): the unrolled per-slot DUS below costs
        # ~3 ms/step at B=64 (4096 tiny ops). A batched Pallas write kernel
        # (group read-merge-write per slot, all slots in one program) was
        # prototyped and is bit-exact on TPU, but its unrolled DMA body
        # made the STEP's Mosaic compile blow up at B=64 (64 kernel
        # instances x 3*B DMA ops), and a grid/fori variant's per-slot
        # serialization lands near DUS cost anyway — so DUS stays.
        for b in range(B):
            upd_k = k[b, 0].astype(dt)[:, None, None, :]   # [n_kv, 1, 1, d]
            upd_v = v[b, 0].astype(dt)[:, None, None, :]
            if owned is not None:  # CP: non-owner preserves the old value
                old_k = jax.lax.dynamic_slice(
                    kd, (0, pid[b], off[b], 0), (kd.shape[0], 1, 1, d))
                old_v = jax.lax.dynamic_slice(
                    vd, (0, pid[b], off[b], 0), (vd.shape[0], 1, 1, d))
                upd_k = jnp.where(owned[b], upd_k, old_k)
                upd_v = jnp.where(owned[b], upd_v, old_v)
            kd = jax.lax.dynamic_update_slice(kd, upd_k, (0, pid[b], off[b], 0))
            vd = jax.lax.dynamic_update_slice(vd, upd_v, (0, pid[b], off[b], 0))
            if quant:
                upd_ks = ks[b, 0][:, None, None]
                upd_vs = vs[b, 0][:, None, None]
                if owned is not None:
                    old_ks = jax.lax.dynamic_slice(
                        ksc, (0, pid[b], off[b]), (ksc.shape[0], 1, 1))
                    old_vs = jax.lax.dynamic_slice(
                        vsc, (0, pid[b], off[b]), (vsc.shape[0], 1, 1))
                    upd_ks = jnp.where(owned[b], upd_ks, old_ks)
                    upd_vs = jnp.where(owned[b], upd_vs, old_vs)
                ksc = jax.lax.dynamic_update_slice(
                    ksc, upd_ks, (0, pid[b], off[b]))
                vsc = jax.lax.dynamic_update_slice(
                    vsc, upd_vs, (0, pid[b], off[b]))
        return rewrap()

    n_touch = (T - 1) // page + 2  # max pages a T-token contiguous run spans
    if n_touch > _MAX_RMW_PAGES:
        if owner is not None:
            raise ValueError(
                "context-parallel writes require the RMW page path; this "
                f"chunk touches {n_touch} pages > {_MAX_RMW_PAGES} "
                "(use a larger page_size or smaller prefill buckets)")
        return _write_tokens_scatter(k_pages, v_pages, k, v, ks, vs,
                                     page_table, positions)

    valid = positions >= 0                       # [B, T]
    # rows are front-packed: entry 0 is the first (lowest) position, or -1
    # for an all-invalid row (idle slot) — then pos0=0 and mask kills it
    pos0 = jnp.maximum(positions[:, 0], 0)       # [B]
    base_lg = pos0 // page
    page_iota = jnp.arange(page, dtype=jnp.int32)
    for b in range(B):
        kb = k[b].astype(dt)                     # [T, n_kv, d]
        vb = v[b].astype(dt)
        for j in range(n_touch):
            lg = base_lg[b] + j
            lg_c = jnp.clip(lg, 0, pps - 1)
            # out-of-range or idle row -> trash page 0 (never read)
            pid = jnp.where((lg < pps) & valid[b, 0], page_table[b, lg_c], 0)
            own = None
            if owner is not None:
                base, width = owner
                lpid = pid - base
                own = (lpid >= 0) & (lpid < width)
                pid = jnp.where(own, lpid, 0)
            page_pos = lg * page + page_iota     # global positions [page]
            t_idx = page_pos - pos0[b]
            t_c = jnp.clip(t_idx, 0, T - 1)
            new_k = jnp.take(kb, t_c, axis=0).transpose(1, 0, 2)  # [n_kv, page, d]
            new_v = jnp.take(vb, t_c, axis=0).transpose(1, 0, 2)
            if quant:
                new_ks = jnp.take(ks[b], t_c, axis=0).T  # [n_kv, page]
                new_vs = jnp.take(vs[b], t_c, axis=0).T
            if j == 0 or own is not None:
                # head page may hold a PREVIOUS chunk's tokens below pos0:
                # read-merge-write. Every later page is append-territory —
                # offsets past the chunk are unwritten (appends only ever
                # move forward) and each will be overwritten before any
                # length-masked read can see it, so pages j>=1 are written
                # blind (no read) with clamped-gather filler. Under CP
                # (own is not None) EVERY page read-merge-writes: a
                # non-owner's clamped local slot 0 holds a real page.
                in_chunk = (t_idx >= 0) & (t_idx < T)
                mask = in_chunk & valid[b, t_c]  # [page]
                if own is not None:
                    mask = mask & own
                cur_k = jax.lax.dynamic_slice(
                    kd, (0, pid, 0, 0), (n_kv, 1, page, d))[:, 0]
                cur_v = jax.lax.dynamic_slice(
                    vd, (0, pid, 0, 0), (n_kv, 1, page, d))[:, 0]
                m = mask[None, :, None]
                new_k = jnp.where(m, new_k, cur_k)
                new_v = jnp.where(m, new_v, cur_v)
                if quant:
                    cur_ks = jax.lax.dynamic_slice(
                        ksc, (0, pid, 0), (n_kv, 1, page))[:, 0]
                    cur_vs = jax.lax.dynamic_slice(
                        vsc, (0, pid, 0), (n_kv, 1, page))[:, 0]
                    new_ks = jnp.where(mask[None, :], new_ks, cur_ks)
                    new_vs = jnp.where(mask[None, :], new_vs, cur_vs)
            kd = jax.lax.dynamic_update_slice(kd, new_k[:, None], (0, pid, 0, 0))
            vd = jax.lax.dynamic_update_slice(vd, new_v[:, None], (0, pid, 0, 0))
            if quant:
                ksc = jax.lax.dynamic_update_slice(
                    ksc, new_ks[:, None], (0, pid, 0))
                vsc = jax.lax.dynamic_update_slice(
                    vsc, new_vs[:, None], (0, pid, 0))
    return rewrap()


def _write_tokens_scatter(k_pages, v_pages, k, v, ks, vs, page_table,
                          positions):
    """HLO-scatter fallback for huge chunks (costs one pool copy)."""
    page = k_pages.shape[2]
    trash = positions < 0
    pos = jnp.where(trash, 0, positions)
    logical_page = pos // page                                   # [B, T]
    page_ids = jnp.take_along_axis(page_table, logical_page, axis=1)
    page_ids = jnp.where(trash, 0, page_ids)
    offs = pos % page
    # adjacent advanced indices on dims (1, 2): result [n_kv, B, T, d]
    kh = jnp.moveaxis(k, 2, 0)
    vh = jnp.moveaxis(v, 2, 0)
    kd = k_pages.data.at[:, page_ids, offs].set(
        kh.astype(k_pages.dtype), mode="drop")
    vd = v_pages.data.at[:, page_ids, offs].set(
        vh.astype(v_pages.dtype), mode="drop")
    ksc, vsc = k_pages.scale, v_pages.scale
    if k_pages.quantized:
        ksc = ksc.at[:, page_ids, offs].set(
            jnp.moveaxis(ks, 2, 0), mode="drop")
        vsc = vsc.at[:, page_ids, offs].set(
            jnp.moveaxis(vs, 2, 0), mode="drop")
    return KVPool(kd, ksc), KVPool(vd, vsc)


class PageAllocator:
    """Host-side refcounting allocator over the physical page pool, with
    optional hash-chained PREFIX CACHING (the vLLM-image capability the
    reference relied on, SURVEY §2.3 row 1).

    Page 0 is reserved (trash). ``allocate`` grows a slot's page list to
    cover ``num_tokens``; ``free`` releases a slot's references.

    Prefix caching: each FULL page of a prompt gets a digest chained over
    every token up to and including that page (sha256 — exact-match, no
    collision handling needed at 2^-128). ``match_prefix`` finds the
    longest cached chain; ``adopt_prefix`` maps those shared pages into a
    slot's table (read-only — the adopting request writes only at
    positions past the cached prefix, which land in later, private
    pages); ``register_prefix`` publishes a slot's freshly written prompt
    pages. Pages keep their content after the last reference drops: they
    move to an LRU of evictable cached pages and are reclaimed only when
    the free list runs dry.
    """

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 pages_per_slot: int, prefix_caching: bool = False):
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self.num_slots = num_slots
        self.prefix_caching = prefix_caching
        self.free_pages: list[int] = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        # page_tables[s] is the authoritative host copy; unused entries point
        # at the trash page 0 (never read thanks to length masking).
        self.page_tables = np.zeros((num_slots, pages_per_slot), dtype=np.int32)
        self.slot_pages: list[list[int]] = [[] for _ in range(num_slots)]
        self.refcount: dict[int, int] = {}
        self._prefix_map: dict[bytes, int] = {}   # digest -> page id
        self._page_digest: dict[int, bytes] = {}  # page id -> digest
        # refcount-0 pages whose content is still a valid cached prefix,
        # oldest-released first (python dicts preserve insertion order)
        self._lru: dict[int, None] = {}
        self.hit_tokens_total = 0  # metrics: prompt tokens served from cache
        # pre-adoption LRU order per slot, kept until commit/rollback: a
        # blocked cache-hit admission retries every engine iteration, and
        # each retry must neither count the hit nor refresh the adopted
        # pages' LRU recency (round-3 advisor finding)
        self._adopt_snapshot: dict[int, list[int]] = {}

    @property
    def num_free_pages(self) -> int:
        return len(self.free_pages)

    @property
    def num_evictable_pages(self) -> int:
        return len(self._lru)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_allocate(self, slot: int, num_tokens: int) -> bool:
        need = self.pages_needed(num_tokens) - len(self.slot_pages[slot])
        return (need <= len(self.free_pages) + len(self._lru)
                and self.pages_needed(num_tokens) <= self.pages_per_slot)

    def _take_page(self) -> int:
        if self.free_pages:
            return self.free_pages.pop()
        if self._lru:  # evict the oldest cached page
            p = next(iter(self._lru))
            if p in self.refcount:
                # the LRU must only ever hold refcount-0 pages; evicting a
                # page some slot still reads would silently corrupt that
                # slot's KV — fail loudly instead (eviction-edge guard)
                raise RuntimeError(
                    f"evictable page {p} is still referenced "
                    f"(refcount={self.refcount[p]}) — LRU invariant broken")
            del self._lru[p]
            d = self._page_digest.pop(p, None)
            if d is not None and self._prefix_map.get(d) == p:
                del self._prefix_map[d]
            return p
        raise MemoryError("KV page pool exhausted")

    def allocate(self, slot: int, num_tokens: int) -> None:
        """Ensure the slot holds enough pages to cover num_tokens tokens."""
        need = self.pages_needed(num_tokens)
        if need > self.pages_per_slot:
            raise ValueError(
                f"sequence of {num_tokens} tokens needs {need} pages > "
                f"pages_per_slot={self.pages_per_slot}"
            )
        have = len(self.slot_pages[slot])
        for i in range(have, need):
            p = self._take_page()
            self.refcount[p] = 1
            self.slot_pages[slot].append(p)
            self.page_tables[slot, i] = p

    def free(self, slot: int) -> None:
        for p in self.slot_pages[slot]:
            if p not in self.refcount:
                # refcount underflow = a double free (the page was already
                # released through another slot list or a stale free): the
                # page may be on the free list or in another slot by now,
                # so continuing would hand the same page to two sequences
                raise RuntimeError(
                    f"double free of page {p} (slot {slot}): page has no "
                    "outstanding references")
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                del self.refcount[p]
                if p in self._page_digest:
                    self._lru[p] = None  # cached: evictable, content kept
                else:
                    self.free_pages.append(p)
        self.slot_pages[slot] = []
        self.page_tables[slot, :] = 0

    # -- prefix caching ----------------------------------------------------

    def _digests(self, tokens, salt: bytes = b"") -> list[bytes]:
        """Chained digest per FULL page of ``tokens``."""
        import hashlib

        out = []
        prev = salt
        for i in range(len(tokens) // self.page_size):
            chunk = tokens[i * self.page_size:(i + 1) * self.page_size]
            h = hashlib.sha256(prev)
            h.update(np.asarray(chunk, np.int64).tobytes())
            prev = h.digest()
            out.append(prev)
        return out

    def _match_digests(self, tokens, salt: bytes = b"") -> list[int]:
        """Page ids of the longest cached prefix — ONE incremental pass
        with early stop at the first miss (an EMA of full-prompt sha256
        passes per admission attempt would be pure waste: a blocked
        admission retries every engine iteration). Capped so at least one
        token remains to prefill (its logits seed sampling).

        ``salt`` seeds the digest chain — multimodal prompts mix a hash
        of their image BYTES in, so identical token streams carrying
        different images (image soft tokens share one placeholder id)
        can never alias."""
        if not self.prefix_caching or len(tokens) <= self.page_size:
            return []
        import hashlib

        cap_pages = (len(tokens) - 1) // self.page_size
        pages: list[int] = []
        prev = salt
        for i in range(cap_pages):
            chunk = tokens[i * self.page_size:(i + 1) * self.page_size]
            h = hashlib.sha256(prev)
            h.update(np.asarray(chunk, np.int64).tobytes())
            prev = h.digest()
            p = self._prefix_map.get(prev)
            if p is None:
                break
            pages.append(p)
        return pages

    def match_prefix(self, tokens, salt: bytes = b"") -> int:
        """Longest cached prefix of ``tokens`` in TOKENS."""
        return len(self._match_digests(tokens, salt)) * self.page_size

    def adopt_prefix(self, slot: int, tokens, salt: bytes = b"") -> int:
        """Map the longest cached prefix into ``slot``'s table (increfs the
        shared pages). Must be called before ``allocate`` grows the slot.
        Returns the number of cached tokens adopted.

        The adoption is PROVISIONAL: the caller either commits it
        (``commit_adopt`` — counts the hit in ``hit_tokens_total``) once
        the admission goes through, or rolls it back (``rollback_adopt``)
        when allocation/validation fails — so a blocked admission
        retrying every engine iteration neither inflates the hit metric
        nor churns the LRU recency of the adopted pages."""
        pages = self._match_digests(tokens, salt)
        if not pages:
            return 0
        assert not self.slot_pages[slot], "adopt_prefix on a non-empty slot"
        self._adopt_snapshot[slot] = list(self._lru)
        for i, p in enumerate(pages):
            self.refcount[p] = self.refcount.get(p, 0) + 1
            self._lru.pop(p, None)  # referenced again: not evictable
            self.slot_pages[slot].append(p)
            self.page_tables[slot, i] = p
        return len(pages) * self.page_size

    def commit_adopt(self, slot: int, hit_tokens: int) -> None:
        """The adoption's admission succeeded: count the cache hit."""
        self._adopt_snapshot.pop(slot, None)
        self.hit_tokens_total += hit_tokens

    def rollback_adopt(self, slot: int) -> None:
        """Undo a provisional ``adopt_prefix``: decref the pages and
        restore the pre-adoption LRU order (a plain ``free`` would
        re-insert the cached pages at the NEWEST recency position, so a
        retrying admission would skew eviction order every iteration)."""
        snap = self._adopt_snapshot.pop(slot, None)
        self.free(slot)
        if snap is not None:
            restored: dict[int, None] = {
                p: None for p in snap if p in self._lru}
            for p in self._lru:  # anything newer keeps its relative order
                restored.setdefault(p, None)
            self._lru = restored

    def register_prefix(self, slot: int, tokens, salt: bytes = b"") -> None:
        """Publish ``slot``'s pages holding full pages of ``tokens`` so
        later prompts with the same prefix can adopt them."""
        if not self.prefix_caching:
            return
        for i, d in enumerate(self._digests(tokens, salt)):
            if i >= len(self.slot_pages[slot]):
                break
            if d in self._prefix_map:
                continue  # identical prefix already cached (dedup)
            p = self.slot_pages[slot][i]
            old = self._page_digest.get(p)
            if old is not None and old != d:
                continue  # page already published under another digest
            self._prefix_map[d] = p
            self._page_digest[p] = d

    def prefix_digests(self) -> "list[bytes]":
        """Snapshot of the published device prefix-cache digests, for the
        replica's /ready membership filter. Server threads call this off
        the engine thread; the engine mutates ``_prefix_map`` without a
        lock, so retry the rare resize-during-copy race instead of adding
        locking to the admission hot path — the filter is a routing hint
        and a one-cycle-stale (or empty) snapshot is harmless."""
        for _ in range(4):
            try:
                return list(self._prefix_map)
            except RuntimeError:
                continue
        return []


class HostKVCache:
    """Host-RAM offload tier for inactive sessions' KV pages
    (AttentionStore/CachedAttention pattern; PAPERS.md).

    Device HBM holds the pages of RESIDENT streams; when a slot is freed
    (finish) or preempted, its full pages spill here — one entry per
    page, keyed by (tenant, chained-prefix digest), the SAME digest the
    PageAllocator's device prefix cache chains (salt included), so a
    returning session's token stream addresses both tiers with one hash
    pass. On a matching resume the engine re-uploads the pages and skips
    straight to decode for the covered tokens instead of re-prefilling
    (engine._adopt_cached_prefix), which turns per-chip session capacity
    from "resident streams" into "resident + parked sessions".

    Payloads are raw pool bytes per page, all layers stacked —
    ``{"k": [n_kv, L, page, d], "v": ..., "ks": [n_kv, L, page] | None,
    "vs": ...}`` (int8 data + f32 scales for quantized pools, the pool
    dtype otherwise) — so a reuse round-trips the exact bytes the device
    wrote and greedy streams stay bit-identical with the tier on or off.

    Keyed by tenant so one tenant's sessions can never be served another
    tenant's KV even on a (cryptographically impossible) digest collision,
    and so per-tenant flushes stay possible. Plain LRU over bytes. The
    engine thread owns the hot paths, but the disaggregated KV handoff
    (openai_api) reads/writes the tier from server threads — a prefill
    replica exports pages to a pulling decode replica, which ingests them
    locally before submitting — so every entry-map touch takes ``_lock``.
    """

    def __init__(self, capacity_bytes: int, page_size: int):
        import threading

        self.capacity_bytes = int(capacity_bytes)
        self.page_size = page_size
        self._entries: "dict[tuple[str, bytes], dict]" = {}
        self._bytes = 0
        self._lock = threading.RLock()
        self.hits = 0        # pages served to a resuming session
        self.misses = 0      # lookups where the chain had no next page
        self.evictions = 0   # pages dropped by LRU pressure
        self.spilled_pages = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    @staticmethod
    def _nbytes(payload: dict) -> int:
        return sum(int(a.nbytes) for a in payload.values() if a is not None)

    def put(self, tenant: str, digest: bytes, payload: dict) -> None:
        with self._lock:
            key = (tenant, digest)
            old = self._entries.pop(key, None)
            if old is not None:  # same prefix re-spilled: refresh recency
                self._bytes -= self._nbytes(old)
            nb = self._nbytes(payload)
            if nb > self.capacity_bytes:
                return  # one page larger than the whole tier: unconfigurable
            self._entries[key] = payload
            self._bytes += nb
            self.spilled_pages += 1
            while self._bytes > self.capacity_bytes and self._entries:
                k, v = next(iter(self._entries.items()))
                if k == key:  # never evict the page just stored
                    break
                del self._entries[k]
                self._bytes -= self._nbytes(v)
                self.evictions += 1

    def match_chain(self, tenant: str, digests: "list[bytes]",
                    start: int) -> "tuple[list[bytes], list[dict]]":
        """(matched digests, payloads) for the longest run of consecutive
        pages present, walking ``digests[start:]``. Pure peek: no stats,
        no recency — a blocked admission re-probes every engine iteration
        and must not spin the hit/miss counters or churn the LRU order.
        Call :meth:`commit` once when the admission actually lands."""
        matched: "list[bytes]" = []
        out: "list[dict]" = []
        with self._lock:
            for d in digests[start:]:
                e = self._entries.get((tenant, d))
                if e is None:
                    break
                matched.append(d)
                out.append(e)
        return matched, out

    def digests(self) -> "list[bytes]":
        """Digest part of every resident entry key (all tenants), for the
        replica's /ready membership filter. Pure peek — no stats, no
        recency. The filter is digest-only: tenancy is still enforced at
        adoption time by the (tenant, digest) entry key, a cross-tenant
        filter hit just fails to match there and re-prefills."""
        with self._lock:
            return [d for (_t, d) in self._entries]

    def export(self, tenant: str, digests: "list[bytes]") \
            -> "list[Optional[dict]]":
        """Payloads for a decode replica pulling a handoff, one per
        digest (None where the page is gone — evicted or never spilled).
        Pure peek like :meth:`match_chain`: the prefill replica's stats
        describe ITS sessions, and the decode replica counts the
        adoption outcome on its side."""
        with self._lock:
            return [self._entries.get((tenant, d)) for d in digests]

    def commit(self, tenant: str, digests: "list[bytes]") -> None:
        """Record a landed admission's outcome: one hit per page served
        (refreshing its LRU recency), or one miss for an empty match.
        Entries evicted between probe and commit are skipped silently —
        the engine uploads the payload objects it captured at probe time,
        so the reuse itself is unaffected."""
        served = 0
        with self._lock:
            for d in digests:
                key = (tenant, d)
                e = self._entries.pop(key, None)
                if e is None:
                    continue
                self._entries[key] = e  # move-to-end: LRU recency
                served += 1
            if served:
                self.hits += served
            else:
                self.misses += 1


def payload_shape_ok(payload, cache_config) -> bool:
    """True iff a host-tier payload has exactly the per-page shapes and
    dtypes this engine's pools expect.

    The engine's own spills are well-formed by construction; this guards
    the HANDOFF ingest path, where payloads crossed a network from a
    replica that may run a different model/topology (or were corrupted in
    flight). A payload that fails is treated as a missing page — the
    adoption chain stops and the remainder re-prefills (degraded, counted)
    instead of crashing in ``np.stack`` or splicing wrong-shaped bytes
    into the pools."""
    cc = cache_config
    if not isinstance(payload, dict):
        return False
    k, v = payload.get("k"), payload.get("v")
    ks, vs = payload.get("ks"), payload.get("vs")
    want = (cc.num_kv_heads, cc.num_layers, cc.page_size, cc.head_dim)
    data_dtype = np.dtype(np.int8 if cc.kv_dtype == "int8"
                          else jnp.dtype(cc.dtype).name)
    for side in (k, v):
        if (not isinstance(side, np.ndarray) or side.shape != want
                or side.dtype != data_dtype):
            return False
    if cc.kv_dtype == "int8":
        want_s = (cc.num_kv_heads, cc.num_layers, cc.page_size)
        for scale in (ks, vs):
            if (not isinstance(scale, np.ndarray) or scale.shape != want_s
                    or scale.dtype != np.dtype(np.float32)):
                return False
    elif ks is not None or vs is not None:
        return False
    return True

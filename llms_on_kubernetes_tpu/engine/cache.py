"""Paged KV cache: device-side page pool + host-side page allocator.

The reference stack got paged attention from the vLLM image (reference
SURVEY §2.3); this is the TPU-native equivalent. Design:

- One global page pool per layer, stacked over layers for ``lax.scan``:
  ``k_pages``/``v_pages`` have shape [L, n_kv, P, page_size, head_dim] —
  **head-major**, so one (head, page) slice is a contiguous [page, d]
  block: the Pallas decode kernel DMAs it HBM→VMEM in a single aligned
  transfer (a head-minor layout puts n_kv in the tiled sublane slot and
  Mosaic rejects the size-1 slice). n_kv is the sharded axis (mesh
  "model") so each TP shard holds its own heads' pages — the pool never
  crosses chips.
- Physical page 0 is reserved as a trash page: padded prompt positions
  write there, so prefill needs no masking on the scatter path. It is never
  allocated to a sequence and never read (length masks exclude it).
- The allocator is plain host Python (free list). Page tables and lengths
  are host numpy, shipped to the device each step as int32 arrays — small
  (slots × pages_per_seq) and latency-irrelevant next to the step itself.

All shapes are static: ``num_pages``, ``page_size``, ``pages_per_slot`` are
fixed at engine start, which is what keeps the decode step at exactly one
compiled executable (XLA retraces on any shape change).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class CacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    num_pages: int = 2048
    page_size: int = 64
    pages_per_slot: int = 32
    dtype: str = "bfloat16"

    @property
    def max_seq_len(self) -> int:
        return self.pages_per_slot * self.page_size

    @property
    def bytes_per_page(self) -> int:
        itemsize = jnp.dtype(self.dtype).itemsize
        return 2 * self.num_layers * self.page_size * self.num_kv_heads * self.head_dim * itemsize


def init_pages(cfg: CacheConfig) -> tuple[jnp.ndarray, jnp.ndarray]:
    shape = (cfg.num_layers, cfg.num_kv_heads, cfg.num_pages, cfg.page_size, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def write_tokens(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new KV for one layer into the page pool.

    k_pages/v_pages: [n_kv, P, page, d] (single layer, head-major)
    k, v:            [B, T, n_kv, d]
    page_table:      [B, pages_per_seq] int32
    positions:       [B, T] int32 token positions; negative => trash page 0
    """
    page = k_pages.shape[2]
    trash = positions < 0
    pos = jnp.where(trash, 0, positions)
    logical_page = pos // page                                   # [B, T]
    page_ids = jnp.take_along_axis(page_table, logical_page, axis=1)
    page_ids = jnp.where(trash, 0, page_ids)
    offs = pos % page
    # adjacent advanced indices on dims (1, 2): result [n_kv, B, T, d]
    kh = jnp.moveaxis(k, 2, 0)
    vh = jnp.moveaxis(v, 2, 0)
    k_pages = k_pages.at[:, page_ids, offs].set(kh, mode="drop")
    v_pages = v_pages.at[:, page_ids, offs].set(vh, mode="drop")
    return k_pages, v_pages


class PageAllocator:
    """Host-side free-list allocator over the physical page pool.

    Page 0 is reserved (trash). ``allocate`` grows a slot's page list to
    cover ``num_tokens``; ``free`` returns a slot's pages to the pool.
    """

    def __init__(self, num_pages: int, page_size: int, num_slots: int, pages_per_slot: int):
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self.num_slots = num_slots
        self.free_pages: list[int] = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        # page_tables[s] is the authoritative host copy; unused entries point
        # at the trash page 0 (never read thanks to length masking).
        self.page_tables = np.zeros((num_slots, pages_per_slot), dtype=np.int32)
        self.slot_pages: list[list[int]] = [[] for _ in range(num_slots)]

    @property
    def num_free_pages(self) -> int:
        return len(self.free_pages)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def can_allocate(self, slot: int, num_tokens: int) -> bool:
        need = self.pages_needed(num_tokens) - len(self.slot_pages[slot])
        return need <= len(self.free_pages) and self.pages_needed(num_tokens) <= self.pages_per_slot

    def allocate(self, slot: int, num_tokens: int) -> None:
        """Ensure the slot owns enough pages to hold num_tokens tokens."""
        need = self.pages_needed(num_tokens)
        if need > self.pages_per_slot:
            raise ValueError(
                f"sequence of {num_tokens} tokens needs {need} pages > "
                f"pages_per_slot={self.pages_per_slot}"
            )
        have = len(self.slot_pages[slot])
        for i in range(have, need):
            if not self.free_pages:
                raise MemoryError("KV page pool exhausted")
            p = self.free_pages.pop()
            self.slot_pages[slot].append(p)
            self.page_tables[slot, i] = p

    def free(self, slot: int) -> None:
        for p in self.slot_pages[slot]:
            self.free_pages.append(p)
        self.slot_pages[slot] = []
        self.page_tables[slot, :] = 0

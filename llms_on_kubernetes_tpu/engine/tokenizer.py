"""Tokenizer abstraction: HF tokenizers in production, byte-level for tests.

The reference delegated tokenization to the serving images (vLLM /
llama-server); here it is part of the engine. ``load_tokenizer`` returns an
object with the small protocol the engine/server need:

    encode(text) -> list[int]
    decode(ids)  -> str
    apply_chat_template(messages) -> list[int]
    eos_ids      -> set[int]

``ByteTokenizer`` (256 bytes + BOS/EOS) keeps every test and the local
CPU path hermetic — no Hub download, mirroring the ramalama solution's
"weights are already on disk" stance (reference ramalama-models/values.yaml:26).
"""

from __future__ import annotations

import os
from typing import Optional, Protocol, Sequence


class TokenizerLike(Protocol):
    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat_template(self, messages: list[dict],
                            tools: Optional[list] = None) -> list[int]: ...
    @property
    def eos_ids(self) -> set[int]: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0-255 are bytes, 256=BOS, 257=EOS."""

    BOS = 256
    EOS = 257
    vocab_size = 258

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict],
                            tools: Optional[list] = None) -> list[int]:
        import json

        text = ""
        if tools:  # render schemas the way tool-aware templates do
            text += f"<tools>{json.dumps(tools, sort_keys=True)}</tools>"
        text += "".join(
            f"<{m.get('role', 'user')}>{m.get('content', '')}</{m.get('role', 'user')}>"
            for m in messages
        )
        return [self.BOS] + self.encode(text)

    @property
    def eos_ids(self) -> set[int]:
        return {self.EOS}


class HFTokenizer:
    """transformers AutoTokenizer wrapper (local files only — zero egress)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict],
                            tools: Optional[list] = None) -> list[int]:
        return self._tok.apply_chat_template(
            messages, tools=tools, tokenize=True, add_generation_prompt=True
        )

    @property
    def eos_ids(self) -> set[int]:
        ids = set()
        if self._tok.eos_token_id is not None:
            ids.add(int(self._tok.eos_token_id))
        # llama-3 style end-of-turn
        for tok in ("<|eot_id|>", "<|im_end|>", "<end_of_turn>"):
            tid = self._tok.convert_tokens_to_ids(tok)
            if tid is not None and tid >= 0 and tid != getattr(self._tok, "unk_token_id", None):
                ids.add(int(tid))
        return ids


class GGUFTokenizer:
    """SentencePiece-style tokenizer from GGUF-embedded vocab metadata.

    llama.cpp ships the tokenizer inside the model file
    (``tokenizer.ggml.tokens`` / ``scores`` / ``token_type``); serving a
    bare .gguf (the local solution's modelPath contract, reference
    ramalama values.yaml) must therefore tokenize from the file itself —
    no tokenizer.json exists on disk. Implements the SPM scheme
    (``tokenizer.ggml.model == "llama"``): ▁-for-space normalization,
    highest-score greedy bigram merging, <0xNN> byte fallback.
    """

    def __init__(self, metadata: dict):
        if metadata.get("tokenizer.ggml.model", "llama") != "llama":
            raise ValueError(
                "only SentencePiece ('llama') GGUF tokenizers are supported; "
                f"got {metadata.get('tokenizer.ggml.model')!r}"
            )
        self.tokens: list[str] = metadata["tokenizer.ggml.tokens"]
        self.scores: list[float] = metadata.get(
            "tokenizer.ggml.scores", [0.0] * len(self.tokens))
        types = metadata.get("tokenizer.ggml.token_type", [1] * len(self.tokens))
        self._rank = {t: i for i, t in enumerate(self.tokens)}
        self._byte_ids = {}
        self._control = set()
        for i, (tok, tt) in enumerate(zip(self.tokens, types)):
            if tt == 6 or (tok.startswith("<0x") and tok.endswith(">")):
                try:
                    self._byte_ids[int(tok[3:-1], 16)] = i
                except ValueError:
                    pass
            if tt == 3:  # control
                self._control.add(i)
        self.bos_id = int(metadata.get("tokenizer.ggml.bos_token_id", 1))
        self.eos_id = int(metadata.get("tokenizer.ggml.eos_token_id", 2))
        self.add_bos = bool(metadata.get("tokenizer.ggml.add_bos_token", True))
        self._prefix = " " if metadata.get(
            "tokenizer.ggml.add_space_prefix", True) else ""
        self._byte_id_set = set(self._byte_ids.values())
        # GGUF files carry the model's own chat template
        # (``tokenizer.chat_template``, same Jinja dialect as HF
        # tokenizer_config.json); llama.cpp's server renders it. Without
        # it a Phi-3 GGUF (the reference's documented local model,
        # reference ramalama-models/README.md:102-107) would get the wrong
        # [INST]-style format.
        self.chat_template: Optional[str] = metadata.get(
            "tokenizer.chat_template") or None

    def _encode_piece(self, text: str) -> list[int]:
        """SPM merge via a bigram heap (linear-log in text length): a
        doubly-linked list of pieces; candidate merges pop best-score
        first, are revalidated against the live list, and push the two new
        neighbour bigrams. Identical output to the naive rescan-everything
        greedy loop (ties broken by position, as SPM does)."""
        import heapq

        n = len(text)
        pieces: list[Optional[str]] = list(text)
        prev = list(range(-1, n - 1))
        nxt = list(range(1, n + 1))
        heap: list[tuple[float, int, int, str]] = []

        def push(i: int) -> None:
            j = nxt[i]
            if j >= n or pieces[i] is None or pieces[j] is None:
                return
            merged = pieces[i] + pieces[j]
            rank = self._rank.get(merged)
            if rank is not None:
                heapq.heappush(heap, (-self.scores[rank], i, j, merged))

        for i in range(n - 1):
            push(i)
        while heap:
            _, i, j, merged = heapq.heappop(heap)
            # stale if either side changed since this candidate was pushed
            if pieces[i] is None or pieces[j] is None or nxt[i] != j:
                continue
            if pieces[i] + pieces[j] != merged:
                continue
            pieces[i] = merged
            pieces[j] = None
            nxt[i] = nxt[j]
            if nxt[j] < n:
                prev[nxt[j]] = i
            if prev[i] >= 0:
                push(prev[i])
            push(i)

        out: list[int] = []
        for p in pieces:
            if p is None:
                continue
            rank = self._rank.get(p)
            if rank is not None:
                out.append(rank)
            else:  # byte fallback
                for b in p.encode("utf-8"):
                    if b in self._byte_ids:
                        out.append(self._byte_ids[b])
        return out

    def encode(self, text: str) -> list[int]:
        norm = (self._prefix + text).replace(" ", "▁")
        ids = self._encode_piece(norm)
        return ([self.bos_id] + ids) if self.add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        out = bytearray()
        for i in ids:
            if i in self._control or not (0 <= i < len(self.tokens)):
                continue
            if i in self._byte_id_set:
                tok = self.tokens[i]
                out.append(int(tok[3:-1], 16))
            else:
                out += self.tokens[i].replace("▁", " ").encode("utf-8")
        return out.decode("utf-8", errors="replace")

    def _encode_with_specials(self, text: str) -> list[int]:
        """Tokenize text that may contain control-token literals (chat
        template output like ``<|end|>``): split on the vocab's control
        strings so each maps to its single id — the SPM merge loop cannot
        assemble them (control pieces carry no merge scores)."""
        import re

        special_by_text = {self.tokens[i]: i for i in self._control
                           if self.tokens[i]}
        if not special_by_text:
            return self._encode_piece(text.replace(" ", "▁"))
        pattern = "(" + "|".join(
            re.escape(s) for s in sorted(special_by_text, key=len,
                                         reverse=True)) + ")"
        ids: list[int] = []
        for part in re.split(pattern, text):
            if not part:
                continue
            if part in special_by_text:
                ids.append(special_by_text[part])
            else:
                ids += self._encode_piece(part.replace(" ", "▁"))
        return ids

    def apply_chat_template(self, messages: list[dict],
                            tools: Optional[list] = None) -> list[int]:
        if self.chat_template:
            try:
                return self._render_chat_template(messages, tools=tools)
            except Exception:
                # malformed template / missing jinja2: fall back to the
                # generic format — but say WHY, once, or every chat
                # request silently degrades with no trace of the cause
                if not getattr(self, "_template_warned", False):
                    self._template_warned = True
                    import logging
                    logging.getLogger(__name__).warning(
                        "chat template rendering failed; falling back to "
                        "the generic [INST] format", exc_info=True)
        # generic [INST]-style template (llama.cpp's default for SPM models)
        text = ""
        for m in messages:
            role, content = m.get("role", "user"), m.get("content", "")
            if role == "system":
                text += f"<<SYS>>\n{content}\n<</SYS>>\n\n"
            elif role == "user":
                text += f"[INST] {content} [/INST]"
            else:
                text += f" {content} "
        return self.encode(text)

    def _render_chat_template(self, messages: list[dict],
                              tools: Optional[list] = None) -> list[int]:
        """Render ``tokenizer.chat_template`` the way HF/llama.cpp do:
        sandboxed Jinja fed messages + bos/eos token strings +
        add_generation_prompt=True, then tokenize with control-token
        splitting. BOS is prepended per add_bos unless the template already
        emitted it."""
        from jinja2.sandbox import ImmutableSandboxedEnvironment

        def raise_exception(msg):
            raise ValueError(msg)

        env = ImmutableSandboxedEnvironment(
            trim_blocks=True, lstrip_blocks=True)
        bos = self.tokens[self.bos_id] if 0 <= self.bos_id < len(self.tokens) else ""
        eos = self.tokens[self.eos_id] if 0 <= self.eos_id < len(self.tokens) else ""
        text = env.from_string(self.chat_template).render(
            messages=messages, add_generation_prompt=True, tools=tools,
            bos_token=bos, eos_token=eos, raise_exception=raise_exception,
        )
        ids = self._encode_with_specials(text)
        if self.add_bos and (not ids or ids[0] != self.bos_id):
            ids = [self.bos_id] + ids
        return ids

    @property
    def eos_ids(self) -> set[int]:
        return {self.eos_id}


def load_tokenizer(model_ref: Optional[str]) -> TokenizerLike:
    if model_ref and os.path.isdir(model_ref):
        for fname in ("tokenizer.json", "tokenizer.model", "tokenizer_config.json"):
            if os.path.exists(os.path.join(model_ref, fname)):
                return HFTokenizer(model_ref)
    if model_ref and model_ref.endswith(".gguf") and os.path.isfile(model_ref):
        from llms_on_kubernetes_tpu.engine.gguf import GGUFFile

        gf = GGUFFile(model_ref)
        try:
            if "tokenizer.ggml.tokens" in gf.metadata:
                return GGUFTokenizer(gf.metadata)
        finally:
            gf.close()
    return ByteTokenizer()

"""Tokenizer abstraction: HF tokenizers in production, byte-level for tests.

The reference delegated tokenization to the serving images (vLLM /
llama-server); here it is part of the engine. ``load_tokenizer`` returns an
object with the small protocol the engine/server need:

    encode(text) -> list[int]
    decode(ids)  -> str
    apply_chat_template(messages) -> list[int]
    eos_ids      -> set[int]

``ByteTokenizer`` (256 bytes + BOS/EOS) keeps every test and the local
CPU path hermetic — no Hub download, mirroring the ramalama solution's
"weights are already on disk" stance (reference ramalama-models/values.yaml:26).
"""

from __future__ import annotations

import os
from typing import Iterable, Optional, Protocol, Sequence


class TokenizerLike(Protocol):
    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat_template(self, messages: list[dict]) -> list[int]: ...
    @property
    def eos_ids(self) -> set[int]: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0-255 are bytes, 256=BOS, 257=EOS."""

    BOS = 256
    EOS = 257
    vocab_size = 258

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict]) -> list[int]:
        text = "".join(
            f"<{m.get('role', 'user')}>{m.get('content', '')}</{m.get('role', 'user')}>"
            for m in messages
        )
        return [self.BOS] + self.encode(text)

    @property
    def eos_ids(self) -> set[int]:
        return {self.EOS}


class HFTokenizer:
    """transformers AutoTokenizer wrapper (local files only — zero egress)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> list[int]:
        return self._tok.apply_chat_template(
            messages, tokenize=True, add_generation_prompt=True
        )

    @property
    def eos_ids(self) -> set[int]:
        ids = set()
        if self._tok.eos_token_id is not None:
            ids.add(int(self._tok.eos_token_id))
        # llama-3 style end-of-turn
        for tok in ("<|eot_id|>", "<|im_end|>", "<end_of_turn>"):
            tid = self._tok.convert_tokens_to_ids(tok)
            if tid is not None and tid >= 0 and tid != getattr(self._tok, "unk_token_id", None):
                ids.add(int(tid))
        return ids


def load_tokenizer(model_ref: Optional[str]) -> TokenizerLike:
    if model_ref and os.path.isdir(model_ref):
        for fname in ("tokenizer.json", "tokenizer.model", "tokenizer_config.json"):
            if os.path.exists(os.path.join(model_ref, fname)):
                return HFTokenizer(model_ref)
    return ByteTokenizer()

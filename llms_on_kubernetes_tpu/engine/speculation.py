"""Speculative decoding: drafters + accept-ratio policy.

Two draft tiers behind one interface (Leviathan et al. 2023; Saxena 2023,
*Prompt Lookup Decoding*):

- ``PromptLookupDrafter`` (tier A, model-free): matches the tail n-gram of
  each slot's committed context (prompt + generated output) against an
  earlier occurrence in the same context and proposes the tokens that
  followed it. Zero extra FLOPs; very effective on RAG / code /
  summarization traffic where the model restates its input.
- ``DraftModelDrafter`` (tier B): a small draft model (random-init by
  registry name for tests, or a ``.gguf`` checkpoint via
  ``engine/gguf.py``) rolled out greedily over a bounded context window.

Drafts ride the engine's fused decode window: the target model scores the
committed token plus all drafted tokens in ONE dispatch
(``forward_verify``), and acceptance under greedy decoding is exact-match —
a pure-performance transform with bit-identical outputs. ``SpecPolicy``
tracks the accept ratio and demotes speculation on adversarial (low-accept)
traffic so wasted verify rows never exceed the re-probe budget.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "PromptLookupDrafter",
    "DraftModelDrafter",
    "SpecPolicy",
    "Speculator",
    "build_speculator",
]

_EMPTY = np.zeros((0,), np.int32)


class PromptLookupDrafter:
    """Model-free prompt-lookup drafter (Saxena 2023).

    ``propose`` scans the last ``window`` tokens of the context for the
    most recent earlier occurrence of the context's tail n-gram (longest
    n-gram first) and returns the continuation that followed it. Host-side
    numpy only — the scan is a vectorized sliding-window compare over at
    most ``window`` tokens per n-gram size.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 window: int = 1024):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.window = window

    def propose(self, ctx: np.ndarray, max_draft: int) -> np.ndarray:
        n = int(ctx.shape[0])
        if max_draft <= 0 or n < self.min_ngram + 1:
            return _EMPTY
        hay = np.asarray(ctx[max(0, n - self.window):], np.int32)
        m = int(hay.shape[0])
        for g in range(min(self.max_ngram, m - 1), self.min_ngram - 1, -1):
            tail = hay[m - g:]
            sub = np.lib.stride_tricks.sliding_window_view(hay, g)
            match = np.all(sub == tail[None, :], axis=1)
            match[-1] = False  # the tail matching itself proposes nothing
            idxs = np.nonzero(match)[0]
            # most recent occurrence wins (local repetition — code, lists,
            # JSON — predicts the continuation best), but only among
            # occurrences with a FULL continuation window: on a run of
            # repeated tokens the latest match ends flush with the tail
            # and would propose a single token where max_draft are there
            # for the taking
            best = _EMPTY
            for i in idxs[::-1]:
                cont = hay[i + g:i + g + max_draft]
                if cont.size == max_draft:
                    return cont.astype(np.int32, copy=True)
                if cont.size > best.size:
                    best = cont
            if best.size:
                return best.astype(np.int32, copy=True)
        return _EMPTY

    def propose_batch(self, ctxs: Sequence[Optional[np.ndarray]],
                      max_draft: int) -> List[np.ndarray]:
        return [self.propose(c, max_draft) if c is not None and c.size
                else _EMPTY for c in ctxs]


class DraftModelDrafter:
    """Draft-model drafter: greedy rollout of a small model over a bounded
    context window.

    The drafter owns a tiny private paged pool (page 0 per layer is the
    pool's trash page, so row pages start at 1): each ``propose_batch``
    re-prefills the [B, window] context tail and runs ``max_draft - 1``
    single-token decode steps, all inside one jitted callable. Stale cache
    contents from the previous call are invisible — attention is bounded
    by the row's current length.
    """

    def __init__(self, params, cfg, *, window: int = 64, max_draft: int = 7,
                 batch: int = 8, page_size: int = 16):
        import jax
        import jax.numpy as jnp

        from llms_on_kubernetes_tpu.engine.cache import CacheConfig, init_pages

        self.params = params
        self.cfg = cfg
        self.window = window
        self.max_draft = max(1, max_draft)
        self.batch = batch
        per_row = -(-(window + self.max_draft) // page_size)
        cc = CacheConfig(
            num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim, num_pages=1 + batch * per_row,
            page_size=page_size, pages_per_slot=per_row, dtype="float32",
        )
        self._kp, self._vp = init_pages(cc)
        self._pt = jnp.asarray(
            1 + np.arange(batch * per_row, dtype=np.int32).reshape(
                batch, per_row))

        from llms_on_kubernetes_tpu.models.decoder import (
            forward_decode, forward_prefill,
        )

        def rollout(params, tokens, lengths, kp, vp, pt):
            logits, kp, vp = forward_prefill(
                params, cfg, tokens, lengths, kp, vp, pt)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            drafts = [nxt]
            for j in range(1, self.max_draft):
                logits, kp, vp = forward_decode(
                    params, cfg, nxt, lengths + j, kp, vp, pt)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drafts.append(nxt)
            return jnp.stack(drafts, axis=1), kp, vp  # [B, max_draft]

        self._rollout = jax.jit(rollout, donate_argnums=(3, 4))

    def propose_batch(self, ctxs: Sequence[Optional[np.ndarray]],
                      max_draft: int) -> List[np.ndarray]:
        import jax.numpy as jnp

        max_draft = min(max_draft, self.max_draft)
        out: List[np.ndarray] = [_EMPTY] * len(ctxs)
        if max_draft <= 0:
            return out
        rows = [i for i, c in enumerate(ctxs) if c is not None and c.size]
        for s in range(0, len(rows), self.batch):
            group = rows[s:s + self.batch]
            toks = np.zeros((self.batch, self.window), np.int32)
            lens = np.zeros((self.batch,), np.int32)
            for b, i in enumerate(group):
                tail = np.asarray(ctxs[i][-self.window:], np.int32)
                toks[b, :tail.shape[0]] = tail
                lens[b] = tail.shape[0]
            drafts, self._kp, self._vp = self._rollout(
                self.params, jnp.asarray(toks), jnp.asarray(lens),
                self._kp, self._vp, self._pt)
            host = np.asarray(drafts)
            for b, i in enumerate(group):
                out[i] = host[b, :max_draft].astype(np.int32, copy=True)
        return out

    def propose(self, ctx: np.ndarray, max_draft: int) -> np.ndarray:
        return self.propose_batch([ctx], max_draft)[0]


@dataclass
class SpecPolicy:
    """Adaptive accept-ratio gate.

    Tracks an EMA of accepted-per-drafted across spec dispatches. When the
    ratio stays below ``min_accept`` after ``min_dispatches`` observations,
    speculation is demoted (wasted verify rows cost real FLOPs on
    adversarial traffic); every ``probe_interval`` decode dispatches while
    demoted, one probe dispatch re-measures so a traffic shift back to
    lookup-friendly content re-enables drafting.
    """

    min_accept: float = 0.3
    min_dispatches: int = 8
    probe_interval: int = 64
    decay: float = 0.9
    dispatches: int = 0
    drafted: int = 0
    accepted: int = 0
    _ema: float = field(default=1.0, repr=False)
    _demoted: bool = field(default=False, repr=False)
    _idle: int = field(default=0, repr=False)

    def note(self, drafted: int, accepted: int) -> None:
        """Record one spec dispatch's outcome (counts exclude the bonus
        token — pure draft hit-rate)."""
        if drafted <= 0:
            return
        self._idle = 0  # a probe (or regular spec dispatch) just ran
        self.dispatches += 1
        self.drafted += drafted
        self.accepted += accepted
        self._ema = (self.decay * self._ema
                     + (1.0 - self.decay) * (accepted / drafted))
        if self.dispatches >= self.min_dispatches:
            self._demoted = self._ema < self.min_accept

    def note_empty(self) -> None:
        """Record a draft attempt that proposed nothing (an accept-ratio
        observation of 0 without inflating the drafted/accepted counters —
        those feed the accept-ratio metric)."""
        self._idle = 0
        self.dispatches += 1
        self._ema = self.decay * self._ema
        if self.dispatches >= self.min_dispatches:
            self._demoted = self._ema < self.min_accept

    def tick(self) -> None:
        """Count one non-spec decode dispatch (drives re-probing)."""
        if self._demoted:
            self._idle += 1

    def should_draft(self) -> bool:
        """Pure check (no side effects): draft unless demoted, probing
        once every probe_interval plain dispatches while demoted. The
        probe's note()/note_empty() resets the idle counter."""
        if not self._demoted:
            return True
        return self._idle >= self.probe_interval

    @property
    def accept_ratio(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0


class Speculator:
    """One engine-facing handle: drafter + policy + window size."""

    def __init__(self, drafter, policy: SpecPolicy, max_draft: int):
        self.drafter = drafter
        self.policy = policy
        self.max_draft = max(1, max_draft)

    def propose_batch(self, ctxs: Sequence[Optional[np.ndarray]],
                      max_draft: Optional[int] = None) -> List[np.ndarray]:
        k = self.max_draft if max_draft is None else min(max_draft,
                                                         self.max_draft)
        return self.drafter.propose_batch(ctxs, k)


def _load_draft_model(ref: str, target_cfg, dtype: str, seed: int):
    """Resolve a draft-model reference to (params, cfg).

    ``*.gguf`` paths load through ``engine/gguf.py``; anything else is a
    registry name given random weights (tests / smoke benchmarks — the
    policy demotes a useless drafter, so a random draft model is safe,
    just pointless). The draft vocab must cover the target's: drafted ids
    are fed straight into the target's verify window.
    """
    if ref.endswith(".gguf") or os.path.exists(ref):
        from llms_on_kubernetes_tpu.engine.gguf import load_gguf_params
        cfg, params = load_gguf_params(ref, dtype=dtype)
    else:
        import jax

        from llms_on_kubernetes_tpu.configs import get_config
        from llms_on_kubernetes_tpu.models.decoder import init_params
        cfg = get_config(ref)
        params = init_params(cfg, jax.random.key(seed), dtype=dtype)
    if cfg.vocab_size < target_cfg.vocab_size:
        raise ValueError(
            f"draft model {ref!r} vocab_size={cfg.vocab_size} < target "
            f"vocab_size={target_cfg.vocab_size}: drafted token ids would "
            "be unverifiable")
    return params, cfg


def build_speculator(engine_config, target_cfg) -> Optional[Speculator]:
    """Build the engine's speculator from its config, or None when
    speculation is off / structurally unavailable (multihost, K=1)."""
    mode = engine_config.speculation
    if mode is None or engine_config.decode_steps <= 1:
        return None
    max_draft = engine_config.decode_steps - 1
    policy = SpecPolicy()
    if mode == "ngram":
        drafter = PromptLookupDrafter()
    elif mode == "draft":
        params, cfg = _load_draft_model(
            engine_config.draft_model, target_cfg,
            engine_config.dtype, engine_config.seed)
        drafter = DraftModelDrafter(params, cfg, max_draft=max_draft)
    else:
        raise ValueError(f"unknown speculation mode {mode!r}")
    return Speculator(drafter, policy, max_draft)

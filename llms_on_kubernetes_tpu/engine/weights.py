"""Checkpoint loading: HuggingFace safetensors → layer-stacked sharded params.

The reference stack's model pods pull weights from the HF Hub into a PVC
cache and let vLLM map them (reference
vllm-models/helm-chart/templates/model-deployments.yaml:26-47). Here the
engine owns that mapping: safetensors shards on disk (same PVC layout,
``/root/.cache/huggingface`` or an explicit dir) are read tensor-by-tensor,
transposed into the decoder's [D, H, hd]-style layouts, stacked across
layers, and ``device_put`` with their ``NamedSharding`` so each chip only
materializes its own shard of the (possibly multi-host) mesh.

Supported families: llama/tinyllama/mistral (same key schema), mixtral
(block_sparse_moe), qwen2 (attention biases), qwen3 (q/k norms), phi3
(fused qkv_proj / gate_up_proj), gemma2/gemma3 (4-norm layers).
"""

from __future__ import annotations

import os
import pathlib
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from llms_on_kubernetes_tpu.configs import ModelConfig

Params = dict[str, Any]


def _open_safetensors(model_dir: str) -> dict[str, Callable[[], np.ndarray]]:
    """Map tensor name -> lazy loader over all *.safetensors files in dir.

    Prefers the native mmap reader (native/loader/libstload.so via
    engine/native_loader.py) when built; falls back to the Python
    ``safetensors`` package."""
    from llms_on_kubernetes_tpu.engine.native_loader import (
        UnsupportedDTypeError, open_native_safetensors,
    )

    native = open_native_safetensors(model_dir)
    if native is not None:
        # per-tensor fallback: a dtype the ctypes bridge can't map (e.g. a
        # future safetensors extension) drops to the Python reader for
        # that tensor instead of failing the whole load
        py_cache: dict = {}

        def with_fallback(name, fn):
            def load():
                try:
                    return fn()
                except UnsupportedDTypeError:
                    if not py_cache:
                        py_cache.update(_open_py_safetensors(model_dir))
                    return py_cache[name]()
            load.__wrapped__ = fn
            return load

        return {name: with_fallback(name, fn) for name, fn in native.items()}
    return _open_py_safetensors(model_dir)


def _open_py_safetensors(model_dir: str) -> dict[str, Callable[[], np.ndarray]]:
    import safetensors

    loaders: dict[str, Callable[[], np.ndarray]] = {}
    files = sorted(pathlib.Path(model_dir).glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {model_dir}")
    for f in files:
        handle = safetensors.safe_open(str(f), framework="numpy")
        for name in handle.keys():
            loaders[name] = (lambda h=handle, n=name: h.get_tensor(n))
    return loaders


def checkpoint_quantization(model_dir: str) -> Optional[dict]:
    """Parse ``quantization_config`` from the checkpoint's config.json.

    Returns None for full-precision checkpoints, else a dict describing
    the format: the reference's default models are a compressed-tensors
    FP8-Dynamic gemma-3 and an AWQ Qwen3 (reference
    vllm-models/helm-chart/values.yaml:2-12)."""
    import json

    path = os.path.join(model_dir, "config.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        qc = json.load(f).get("quantization_config")
    if not qc:
        return None
    method = qc.get("quant_method")
    if method == "compressed-tensors":
        # compressed-tensors is a CONTAINER format: inspect the declared
        # scheme — only float (FP8) weight quantization is supported here.
        # W4A16 packs weights differently ('weight_packed') and int8
        # schemes may carry zero points; loading those as w*scale would
        # silently serve wrong weights.
        for group in (qc.get("config_groups") or {}).values():
            w = (group or {}).get("weights") or {}
            if w.get("type", "float") != "float" or int(
                    w.get("num_bits", 8)) != 8:
                raise ValueError(
                    f"unsupported compressed-tensors weight scheme "
                    f"{w.get('type')}/{w.get('num_bits')}-bit (only FP8 "
                    f"float weights)")
        return {"method": "fp8"}
    if method == "awq":
        version = qc.get("version", "gemm")
        if version != "gemm":
            raise ValueError(
                f"unsupported AWQ version {version!r} (only 'gemm' packing)")
        return {"method": "awq", "bits": int(qc.get("bits", 4)),
                "group_size": int(qc.get("group_size", 128))}
    raise ValueError(f"unsupported quant_method {method!r} "
                     f"(supported: compressed-tensors fp8, awq)")


class _Fetch:
    """Reads HF tensors with layout transforms; pre-quantized checkpoint
    weights (compressed-tensors FP8 / AWQ) are dequantized transparently,
    so every downstream transpose/split/stack path sees plain arrays."""

    def __init__(self, loaders, quant: Optional[dict] = None):
        self.loaders = loaders
        self.quant = quant

    def _present(self, name: str) -> bool:
        if name in self.loaders:
            return True
        return (self.quant is not None and self.quant["method"] == "awq"
                and name.endswith(".weight")
                and name[:-len("weight")] + "qweight" in self.loaders)

    def _resolve(self, name: str) -> str:
        """Multimodal wrappers nest the text model: a gemma-3 checkpoint
        stores `model.language_model.layers.*` (+ `model.vision_tower.*`,
        `model.multi_modal_projector.*`); plain decoders use
        `model.layers.*`. Resolve transparently so one layer map serves
        both (including quantized storage, where only `qweight` exists)."""
        if self._present(name):
            return name
        if name.startswith("model."):
            for alt in ("model.language_model." + name[len("model."):],
                        "language_model." + name):  # language_model.model.*
                if self._present(alt):
                    return alt
        alt = "model." + name
        if self._present(alt):
            return alt
        return name

    def __call__(self, name: str) -> np.ndarray:
        name = self._resolve(name)
        if self.quant is not None and name.endswith(".weight"):
            from llms_on_kubernetes_tpu.ops.quant import (
                awq_dequantize, fp8_dequantize,
            )

            base = name[:-len("weight")]
            if (self.quant["method"] == "awq"
                    and base + "qweight" in self.loaders):
                w = awq_dequantize(
                    np.asarray(self.loaders[base + "qweight"]()),
                    np.asarray(self.loaders[base + "qzeros"]()),
                    np.asarray(self.loaders[base + "scales"]()),
                    bits=self.quant["bits"],
                )
                return np.ascontiguousarray(w.T)   # HF orientation [out, in]
            if (self.quant["method"] == "fp8" and name in self.loaders
                    and base + "weight_scale" in self.loaders):
                return fp8_dequantize(
                    np.asarray(self.loaders[name]()),
                    np.asarray(self.loaders[base + "weight_scale"]()),
                )
        if name not in self.loaders:
            raise KeyError(
                f"checkpoint is missing tensor {name!r} "
                f"(have {len(self.loaders)} tensors)"
            )
        return np.asarray(self.loaders[name]())

    def has(self, name: str) -> bool:
        """Presence check that sees through quantized storage names and
        multimodal prefix nesting."""
        return self._present(self._resolve(name))

    def linear(self, name: str, out_reshape=None) -> np.ndarray:
        """HF linear weight [out, in] -> [in, out] (+ optional reshape)."""
        w = self(name).T
        if out_reshape is not None:
            w = w.reshape(w.shape[0], *out_reshape)
        return w

    def grouped(self, name: str, out_shape: tuple):
        """Native group-quantized tensor for an AWQ-packed linear, or
        None when the tensor isn't AWQ-packed (falls back to dequant).
        ``name`` is the HF `.weight` name; ``out_shape`` the logical
        output dims of OUR layout (the AWQ in-axis is the contraction)."""
        if self.quant is None or self.quant["method"] != "awq":
            return None
        name = self._resolve(name)
        base = name[:-len("weight")]
        if base + "qweight" not in self.loaders:
            return None
        from llms_on_kubernetes_tpu.ops.quant import awq_group_tensors

        return awq_group_tensors(
            np.asarray(self.loaders[base + "qweight"]()),
            np.asarray(self.loaders[base + "qzeros"]()),
            np.asarray(self.loaders[base + "scales"]()),
            bits=self.quant["bits"], out_shape=out_shape,
        )


def hf_layer_maps(cfg: ModelConfig, fetch: _Fetch, i: int,
                  preloaded: "Optional[Params]" = None) -> Params:
    """Return our per-layer param dict for HF layer ``i``.

    ``preloaded`` short-circuits named entries (the AWQ-native loader
    passes GroupQTensors so the throwaway f32 dequant of those linears
    never runs — it would double the host-side load work per layer)."""
    H, KV, hd, D, F = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.hidden_size, cfg.intermediate_size
    p = f"model.layers.{i}."
    pre = preloaded or {}
    out: Params = dict(pre)

    # --- attention ------------------------------------------------------
    if not {"wq", "wk", "wv"} <= pre.keys():
        try:
            out["wq"] = pre.get("wq") if "wq" in pre else fetch.linear(
                p + "self_attn.q_proj.weight", (H, hd))
            out["wk"] = pre.get("wk") if "wk" in pre else fetch.linear(
                p + "self_attn.k_proj.weight", (KV, hd))
            out["wv"] = pre.get("wv") if "wv" in pre else fetch.linear(
                p + "self_attn.v_proj.weight", (KV, hd))
        except KeyError:
            # phi3 fused qkv: [(H + 2KV) * hd, D]
            qkv = fetch(p + "self_attn.qkv_proj.weight")
            q, k, v = np.split(qkv, [H * hd, (H + KV) * hd], axis=0)
            out["wq"] = q.T.reshape(D, H, hd)
            out["wk"] = k.T.reshape(D, KV, hd)
            out["wv"] = v.T.reshape(D, KV, hd)
    if "wo" not in pre:
        wo = fetch(p + "self_attn.o_proj.weight")  # [D, H*hd]
        out["wo"] = wo.T.reshape(H, hd, D)

    if cfg.attention_bias:
        out["bq"] = fetch(p + "self_attn.q_proj.bias").reshape(H, hd)
        out["bk"] = fetch(p + "self_attn.k_proj.bias").reshape(KV, hd)
        out["bv"] = fetch(p + "self_attn.v_proj.bias").reshape(KV, hd)
    if cfg.qk_norm:
        out["q_norm"] = fetch(p + "self_attn.q_norm.weight")
        out["k_norm"] = fetch(p + "self_attn.k_norm.weight")

    # --- norms ----------------------------------------------------------
    out["attn_norm"] = fetch(p + "input_layernorm.weight")
    if cfg.post_norms:  # gemma2/3: 4 norms per layer
        out["attn_post_norm"] = fetch(p + "post_attention_layernorm.weight")
        out["mlp_norm"] = fetch(p + "pre_feedforward_layernorm.weight")
        out["mlp_post_norm"] = fetch(p + "post_feedforward_layernorm.weight")
    else:
        out["mlp_norm"] = fetch(p + "post_attention_layernorm.weight")

    # --- mlp ------------------------------------------------------------
    if cfg.is_moe:
        E = cfg.num_experts
        gates, ups, downs = [], [], []
        if fetch.has(p + "block_sparse_moe.gate.weight"):
            # Mixtral naming: block_sparse_moe.{gate, experts.N.w1/w2/w3}
            out["router"] = fetch(p + "block_sparse_moe.gate.weight").T  # [D, E]
            for e in range(E):
                ep = p + f"block_sparse_moe.experts.{e}."
                gates.append(fetch(ep + "w1.weight").T)   # [D, F]
                ups.append(fetch(ep + "w3.weight").T)     # [D, F]
                downs.append(fetch(ep + "w2.weight").T)   # [F, D]
        else:
            # Qwen3-MoE naming: mlp.{gate, experts.N.gate/up/down_proj}
            out["router"] = fetch(p + "mlp.gate.weight").T
            for e in range(E):
                ep = p + f"mlp.experts.{e}."
                gates.append(fetch(ep + "gate_proj.weight").T)
                ups.append(fetch(ep + "up_proj.weight").T)
                downs.append(fetch(ep + "down_proj.weight").T)
        out["w_gate"] = np.stack(gates)
        out["w_up"] = np.stack(ups)
        out["w_down"] = np.stack(downs)
    else:
        if not {"w_gate", "w_up"} <= pre.keys():
            try:
                out["w_gate"] = (pre["w_gate"] if "w_gate" in pre
                                 else fetch.linear(p + "mlp.gate_proj.weight"))
                out["w_up"] = (pre["w_up"] if "w_up" in pre
                               else fetch.linear(p + "mlp.up_proj.weight"))
            except KeyError:
                gu = fetch(p + "mlp.gate_up_proj.weight")  # phi3 fused [2F, D]
                g, u = np.split(gu, 2, axis=0)
                out["w_gate"] = g.T
                out["w_up"] = u.T
        if "w_down" not in pre:
            out["w_down"] = fetch.linear(p + "mlp.down_proj.weight")
    return out


class WeightsPreload:
    """Overlap safetensors open/mmap with other startup work.

    Cold-start attack: ``_open_safetensors`` walks the checkpoint dir,
    parses every shard header and (native stload path) mmaps the tensor
    data — pure host I/O with no JAX dependency, so it can run in a
    background thread WHILE the distributed runtime initializes and the
    device mesh is built. Start one before mesh init; pass it to
    :func:`load_hf_params` (via ``Engine(weights_preload=...)``) and the
    load phase begins with the loaders already open instead of paying
    the walk+header+mmap cost serially.
    """

    def __init__(self, model_dir: str):
        self.model_dir = model_dir
        self._loaders: Optional[dict] = None
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="weights-preload")
        self._thread.start()

    def _run(self) -> None:
        try:
            self._loaders = _open_safetensors(self.model_dir)
        except BaseException as e:  # re-raised on the consumer thread
            self._error = e

    def loaders(self) -> dict:
        """Join the preload and return its loaders (or raise its error)."""
        self._thread.join()
        if self._error is not None:
            raise self._error
        assert self._loaders is not None
        return self._loaders


def load_hf_params(
    cfg: ModelConfig,
    model_dir: str,
    mesh=None,
    dtype: Optional[str] = None,
    quantization: Optional[str] = None,
    preload: Optional[WeightsPreload] = None,
) -> Params:
    """Load a HF checkpoint directory into (optionally mesh-sharded) params.

    ``quantization="int8"`` quantizes the matmul weights host-side before
    device placement. Pre-quantized checkpoints — compressed-tensors FP8
    and AWQ, the reference's default models (values.yaml:2-12; SURVEY §7
    hard-part 5) — are detected from config.json's quantization_config,
    dequantized tensor-by-tensor, and re-quantized to the TPU-native
    serving format (weight-only int8, same 1 byte/param device footprint).
    ``quantization="fp8"|"awq"`` additionally asserts the checkpoint IS
    that format (deployment intent check); with None/"int8" the format is
    auto-detected.
    """
    from llms_on_kubernetes_tpu.ops.quant import (
        _LAYER_REDUCE_AXES, QTensor, SUPPORTED_QUANTIZATIONS, quantize,
        reduce_axes_for,
    )

    if quantization not in SUPPORTED_QUANTIZATIONS:
        raise ValueError(
            f"unknown quantization {quantization!r} "
            f"(supported: {[q for q in SUPPORTED_QUANTIZATIONS if q]})"
        )
    ckpt_quant = checkpoint_quantization(model_dir)
    if quantization in ("fp8", "awq"):
        found = ckpt_quant["method"] if ckpt_quant else None
        if found != quantization:
            raise ValueError(
                f"quantization={quantization!r} requested but the checkpoint "
                f"at {model_dir} is {found or 'full-precision'}"
            )
    dt = jnp.dtype(dtype or cfg.dtype)
    if preload is not None and preload.model_dir == model_dir:
        loaders = preload.loaders()
    else:
        loaders = _open_safetensors(model_dir)
    fetch = _Fetch(loaders, quant=ckpt_quant)

    # Pre-quantized checkpoints always serve int8 (their weights are
    # already <= 8-bit); bf16 checkpoints only when asked.
    quantize_now = quantization == "int8" or ckpt_quant is not None

    # Native AWQ execution (round 4): the group format is served as-is
    # (GroupQTensor — int4 data + per-group scales/zeros, ops/quant.py)
    # instead of the dequant → per-channel-int8 approximation. Only
    # tensors stored 1:1 in AWQ packing qualify; anything the layer map
    # splits/fuses (phi3 fused qkv, MoE expert stacks) falls back to the
    # dequant path below. (HF name, our out_shape) per our param name:
    awq_native = ckpt_quant is not None and ckpt_quant["method"] == "awq"
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    awq_sources = {
        "wq": ("self_attn.q_proj.weight", (H, hd)),
        "wk": ("self_attn.k_proj.weight", (KV, hd)),
        "wv": ("self_attn.v_proj.weight", (KV, hd)),
        "wo": ("self_attn.o_proj.weight", (cfg.hidden_size,)),
        "w_gate": ("mlp.gate_proj.weight", (cfg.intermediate_size,)),
        "w_up": ("mlp.up_proj.weight", (cfg.intermediate_size,)),
        "w_down": ("mlp.down_proj.weight", (cfg.hidden_size,)),
    }

    per_layer: list[Params] = []
    for i in range(cfg.num_layers):
        pre: Params = {}
        if awq_native and not cfg.is_moe:
            # grouped tensors FIRST so hf_layer_maps never runs the
            # throwaway f32 dequant for them (it would double host-side
            # load work per layer — cold-start cost)
            for name, (src, out_shape) in awq_sources.items():
                g = fetch.grouped(f"model.layers.{i}.{src}", out_shape)
                if g is not None:
                    pre[name] = g
        lm = hf_layer_maps(cfg, fetch, i, preloaded=pre)
        if quantize_now:
            # quantize BEFORE stacking: host RAM holds at most one layer
            # of dequantized f32, never the whole model
            from llms_on_kubernetes_tpu.ops.quant import GroupQTensor

            for name in _LAYER_REDUCE_AXES:
                w = lm.get(name)
                if w is None or isinstance(w, GroupQTensor):
                    continue
                axes = tuple(a - 1 for a in reduce_axes_for(name, w.ndim + 1))
                lm[name] = quantize(w, axes)
        per_layer.append(lm)

    def stack(key):
        from llms_on_kubernetes_tpu.ops.quant import GroupQTensor

        vals = [pl[key] for pl in per_layer]
        if isinstance(vals[0], GroupQTensor):
            return GroupQTensor(np.stack([v.data for v in vals]),
                                np.stack([v.scale for v in vals]),
                                np.stack([v.zero_scaled for v in vals]),
                                vals[0].out_shape,
                                packed=vals[0].packed,
                                group_axis=vals[0].group_axis)
        if isinstance(vals[0], QTensor):
            return QTensor(np.stack([v.data for v in vals]),
                           np.stack([v.scale for v in vals]))
        return np.stack(vals).astype(dt)

    layers = {k: stack(k) for k in per_layer[0]}
    params: Params = {
        "embed": np.asarray(fetch("model.embed_tokens.weight")).astype(dt),
        "final_norm": np.asarray(fetch("model.norm.weight")).astype(dt),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = fetch.linear("lm_head.weight").astype(dt)
    if cfg.vision is not None:
        from llms_on_kubernetes_tpu.models.vision import (
            load_qwen3vl_vision_params, load_vision_params,
        )

        loader = (load_qwen3vl_vision_params
                  if cfg.vision.family == "qwen3vl" else load_vision_params)
        params["vision"] = loader(cfg.vision, fetch, dtype=dt)

    if mesh is not None:
        from llms_on_kubernetes_tpu.parallel.sharding import shard_params

        params = shard_params(params, cfg, mesh)
    else:
        params = jax.tree.map(jnp.asarray, params)  # QTensor is a pytree node
    return params


def hf_hub_cache(cache_dir: Optional[str] = None) -> str:
    """The hub cache directory, shared by resolution AND download.

    One helper so ``resolve_model_dir`` and ``hub.download_snapshot`` can
    never disagree on where snapshots live: explicit ``cache_dir`` (its
    ``hub/`` subdir, the HF layout) > ``HF_HUB_CACHE`` (points directly at
    the hub dir) > ``HF_HOME`` > ``~/.cache/huggingface`` — the PVC mount
    point in the charts (templates/model-deployments.yaml)."""
    if cache_dir:
        return os.path.join(cache_dir, "hub")
    env = os.environ.get("HF_HUB_CACHE", "").strip()
    if env:
        return os.path.expanduser(env)
    home = os.path.expanduser(os.environ.get("HF_HOME", "~/.cache/huggingface"))
    return os.path.join(home, "hub")


_SHARD_RE = r".*-\d{4,6}-of-\d{4,6}\.safetensors$"


def _snapshot_complete(snap: pathlib.Path, require_tokenizer: bool = True) -> bool:
    """True when a cache snapshot holds a COMPLETE, loadable checkpoint.

    A checkpoint interrupted mid-download leaves some files symlinked and
    the rest missing; treating that as resolvable would short-circuit the
    resume download and crash-loop the pod on load. Complete means:
    ``config.json`` present (``from_hf_config`` needs it right after
    resolution), and either every shard in the index's weight_map exists,
    or — with no index downloaded yet — at least one safetensors file none
    of which is shard-named (shard names imply an index is still coming;
    downloads are concurrent, so file arrival order proves nothing)."""
    import json as _json
    import re as _re

    if not (snap / "config.json").is_file():
        return False
    # at least one tokenizer artifact (all are in hub._ALLOW_PATTERNS):
    # without this, a download killed after weights-but-before-tokenizer
    # would resolve, never resume, and silently serve via ByteTokenizer.
    # ``require_tokenizer=False`` grandfathers pre-existing weights-only
    # snapshots (hand-populated PVC, or one written by an older release)
    # when a resume download is impossible — see hub.ensure_model_dir.
    if require_tokenizer and not any(
            (snap / t).is_file() for t in
            ("tokenizer.json", "tokenizer.model", "tokenizer_config.json",
             "vocab.json")):
        return False
    idx = snap / "model.safetensors.index.json"
    if idx.is_file():
        try:
            weight_map = _json.loads(idx.read_text()).get("weight_map", {})
        except (OSError, ValueError):
            return False
        shards = set(weight_map.values())
        return bool(shards) and all((snap / s).is_file() for s in shards)
    files = [f.name for f in snap.glob("*.safetensors")]
    return bool(files) and not any(_re.match(_SHARD_RE, f) for f in files)


def resolve_model_dir(model_ref: str, cache_dir: Optional[str] = None,
                      require_tokenizer: bool = True) -> str:
    """Resolve a local dir or a HF-cache snapshot path for ``model_ref``.

    Mirrors the reference's PVC cache convention: weights live under
    ``/root/.cache/huggingface`` (reference model-deployments.yaml:45-47).
    Zero-egress environments must pre-populate the cache (the reference's
    first-boot Hub download happens out-of-band here).
    """
    if os.path.isdir(model_ref):
        return model_ref
    hub_dir = pathlib.Path(hf_hub_cache(cache_dir))
    from llms_on_kubernetes_tpu.configs import hf_repo_for

    # a registry name ("llama-3-8b") caches under its canonical repo id
    canonical = hf_repo_for(model_ref)
    refs = [model_ref] + ([canonical] if canonical and canonical != model_ref else [])
    for ref in refs:
        repo_dir = hub_dir / ("models--" + ref.replace("/", "--"))
        snaps = sorted((repo_dir / "snapshots").glob("*")) if repo_dir.exists() else []
        for snap in snaps:
            if _snapshot_complete(snap, require_tokenizer=require_tokenizer):
                return str(snap)
    raise FileNotFoundError(
        f"no local checkpoint for {model_ref!r}; expected a directory or a "
        f"HF cache snapshot for one of {refs} under {hub_dir}"
    )

"""Multi-tenant LoRA adapters: PEFT loading + on-device LRU slot cache.

One engine serves many fine-tunes of its base model (ROADMAP item 3,
punica/S-LoRA style): each configured adapter is loaded by id through the
hub download path (``engine/hub.py::ensure_adapter_dir``), validated
against the base model's shapes, and uploaded into one of a fixed number
of DEVICE slots — the per-target ``LoRAStack``s living inside
``params["layers"]`` (``ops/lora.py``). Requests reference adapters by
name; admission pins the adapter's slot for the request's lifetime
(refcounted, exactly like grammar device-table residency), and slots are
recycled LRU when a new adapter needs one. Slot residency changes are
pure buffer updates (``.at[slot].set``) — the compiled decode program
never changes, so the adapter-free fast path costs nothing.

Stats (hits/misses/evictions/load seconds) are plain host counters; the
serving loop bridges them into ``llm_adapter_cache_*`` Prometheus series
with the same delta pattern it uses for preemptions.
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

# targets the engine can attach stacks for: our weight name -> the PEFT
# module suffix that maps to it
PEFT_MODULES = {
    "wq": "q_proj", "wk": "k_proj", "wv": "v_proj", "wo": "o_proj",
    "w_gate": "gate_proj", "w_up": "up_proj", "w_down": "down_proj",
}
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")

_TENSOR_RE = re.compile(
    r"layers\.(\d+)\.(?:self_attn|mlp)\.([a-z_]+)_proj\.lora_([AB])\.weight$")


class AdapterError(ValueError):
    """A configured adapter failed to load or validate (corrupt file,
    rank/shape mismatch, unsupported target)."""


@dataclass
class LoadedAdapter:
    """Host-cached, validated factors for one adapter, already in the
    engine's layouts and padded to the stack rank (alpha/r folded into b)."""

    name: str
    rank: int
    alpha: float
    # target -> (a [L, *in_dims, max_rank] f32, b [L, max_rank, *out_dims] f32)
    factors: dict = field(default_factory=dict)


def _expected_shapes(cfg) -> dict:
    """Per-target (A, B) tensor shapes of ONE layer in PEFT layout
    (lora_A [r, in_features], lora_B [out_features, r])."""
    D, F = cfg.hidden_size, cfg.intermediate_size
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    return {
        "wq": (D, H * hd), "wk": (D, KV * hd), "wv": (D, KV * hd),
        "wo": (H * hd, D),
        "w_gate": (D, F), "w_up": (D, F), "w_down": (F, D),
    }


def _to_engine_layout(target: str, which: str, w: np.ndarray, cfg):
    """One PEFT tensor -> the engine's factor layout for ``target``.

    A [r, in] -> a [*in_dims, r];  B [out, r] -> b [r, *out_dims] — with
    in/out factored into the decoder's explicit head axes."""
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    w = np.asarray(w, np.float32)
    if which == "A":
        a = w.T  # [in, r]
        if target == "wo":
            return a.reshape(H, hd, -1)
        return a
    b = w.T  # [r, out]
    if target in ("wq",):
        return b.reshape(b.shape[0], H, hd)
    if target in ("wk", "wv"):
        return b.reshape(b.shape[0], KV, hd)
    return b


def load_adapter(name: str, adapter_dir: str, cfg, max_rank: int,
                 targets: tuple = DEFAULT_TARGETS) -> LoadedAdapter:
    """Read + validate a PEFT LoRA checkpoint against the base model.

    Rejects (``AdapterError``): unreadable/corrupt safetensors, a config
    rank that disagrees with the tensors, rank > the engine's stack rank,
    tensors for targets the engine has no stack for, wrong shapes, and an
    A without its B (or vice versa). The result is zero-padded to
    ``max_rank`` and carries the alpha/r scale folded into ``b`` so upload
    is a plain buffer copy.
    """
    cfg_path = os.path.join(adapter_dir, "adapter_config.json")
    try:
        with open(cfg_path) as f:
            acfg = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise AdapterError(f"adapter {name!r}: bad adapter_config.json: {e}")
    rank = int(acfg.get("r", 0))
    alpha = float(acfg.get("lora_alpha", rank))
    if rank <= 0:
        raise AdapterError(f"adapter {name!r}: invalid rank r={rank}")
    if rank > max_rank:
        raise AdapterError(
            f"adapter {name!r}: rank {rank} exceeds the engine's adapter "
            f"rank capacity {max_rank} (raise --adapter-rank)")

    module_to_target = {v: k for k, v in PEFT_MODULES.items()}
    st_path = os.path.join(adapter_dir, "adapter_model.safetensors")
    try:
        from safetensors import safe_open

        tensors: dict[tuple, np.ndarray] = {}
        with safe_open(st_path, framework="numpy") as st:
            for key in st.keys():
                m = _TENSOR_RE.search(key)
                if m is None:
                    raise AdapterError(
                        f"adapter {name!r}: unrecognized tensor {key!r}")
                layer, module, which = int(m.group(1)), m.group(2), m.group(3)
                target = module_to_target.get(module + "_proj")
                if target is None:
                    raise AdapterError(
                        f"adapter {name!r}: unsupported module "
                        f"{module}_proj in {key!r}")
                if target not in targets:
                    raise AdapterError(
                        f"adapter {name!r}: target {target} ({module}_proj) "
                        f"is not enabled on this engine "
                        f"(enabled: {','.join(targets)})")
                if layer >= cfg.num_layers:
                    raise AdapterError(
                        f"adapter {name!r}: layer {layer} out of range for "
                        f"{cfg.num_layers}-layer base model")
                tensors[(target, layer, which)] = st.get_tensor(key)
    except AdapterError:
        raise
    except Exception as e:  # unreadable / truncated / not safetensors
        raise AdapterError(
            f"adapter {name!r}: cannot read {st_path}: {e}")
    if not tensors:
        raise AdapterError(f"adapter {name!r}: no LoRA tensors found")

    expect = _expected_shapes(cfg)
    L = cfg.num_layers
    factors: dict = {}
    seen_targets = sorted({t for t, _, _ in tensors})
    for target in seen_targets:
        in_f, out_f = expect[target]
        a_one = _to_engine_layout(target, "A",
                                  np.zeros((rank, in_f)), cfg)
        b_one = _to_engine_layout(target, "B",
                                  np.zeros((out_f, rank)), cfg)
        a = np.zeros((L,) + a_one.shape[:-1] + (max_rank,), np.float32)
        b = np.zeros((L, max_rank) + b_one.shape[1:], np.float32)
        for layer in range(L):
            wa = tensors.get((target, layer, "A"))
            wb = tensors.get((target, layer, "B"))
            if (wa is None) != (wb is None):
                raise AdapterError(
                    f"adapter {name!r}: layer {layer} {target} has lora_"
                    f"{'A' if wa is None else 'B'} missing")
            if wa is None:
                continue
            if tuple(wa.shape) != (rank, in_f):
                raise AdapterError(
                    f"adapter {name!r}: {target} layer {layer} lora_A shape "
                    f"{tuple(wa.shape)} != expected {(rank, in_f)} "
                    f"(rank/shape mismatch)")
            if tuple(wb.shape) != (out_f, rank):
                raise AdapterError(
                    f"adapter {name!r}: {target} layer {layer} lora_B shape "
                    f"{tuple(wb.shape)} != expected {(out_f, rank)} "
                    f"(rank/shape mismatch)")
            a[layer, ..., :rank] = _to_engine_layout(target, "A", wa, cfg)
            # alpha/r folds into b so the batched path needs no extra scale
            b[layer, :rank] = _to_engine_layout(
                target, "B", wb, cfg) * (alpha / rank)
        factors[target] = (a, b)
    return LoadedAdapter(name=name, rank=rank, alpha=alpha, factors=factors)


class AdapterManager:
    """Name -> device-slot residency with LRU recycling.

    ``acquire`` returns the adapter's slot, loading + uploading on a miss
    (evicting the least-recently-used UNPINNED slot if none is free), or
    None when every slot is pinned by running requests — the admission
    waits, exactly like grammar-table or page-pool pressure. ``release``
    drops a request's pin. Loaded factors are host-cached, so re-loading
    an evicted adapter is an upload, not a disk read.
    """

    def __init__(self, registry: dict, num_slots: int,
                 loader: Callable[[str, str], LoadedAdapter],
                 upload: Callable[[int, LoadedAdapter], None]):
        self.registry = dict(registry)
        self.num_slots = int(num_slots)
        self._loader = loader
        self._upload = upload
        self.slot_name: list[Optional[str]] = [None] * self.num_slots
        self.slot_refs = [0] * self.num_slots
        self._slot_touch = [0] * self.num_slots
        self._tick = 0
        self._host_cache: dict[str, LoadedAdapter] = {}
        self.stats = {"hits": 0, "misses": 0, "evictions": 0}
        self.load_times: list[float] = []  # drained by the metrics bridge

    def known(self, name: str) -> bool:
        return name in self.registry

    def names(self) -> list[str]:
        return sorted(self.registry)

    def slot_of(self, name: str) -> Optional[int]:
        try:
            return self.slot_name.index(name)
        except ValueError:
            return None

    def acquire(self, name: str) -> Optional[int]:
        if name not in self.registry:
            raise KeyError(f"adapter {name!r} is not configured")
        slot = self.slot_of(name)
        if slot is not None:
            self.stats["hits"] += 1
            self._pin(slot)
            return slot
        # miss: free slot first, else LRU among unpinned residents
        victims = [s for s in range(self.num_slots)
                   if self.slot_name[s] is None]
        if not victims:
            victims = sorted(
                (s for s in range(self.num_slots) if self.slot_refs[s] == 0),
                key=lambda s: self._slot_touch[s])
        if not victims:
            return None  # every slot pinned by running requests; wait
        slot = victims[0]
        self.stats["misses"] += 1
        if self.slot_name[slot] is not None:
            self.stats["evictions"] += 1
        t0 = time.perf_counter()
        loaded = self._host_cache.get(name)
        if loaded is None:
            loaded = self._loader(name, self.registry[name])
            self._host_cache[name] = loaded
        self._upload(slot, loaded)
        self.load_times.append(time.perf_counter() - t0)
        self.slot_name[slot] = name
        self.slot_refs[slot] = 0
        self._pin(slot)
        return slot

    def release(self, slot: int) -> None:
        if 0 <= slot < self.num_slots and self.slot_refs[slot] > 0:
            self.slot_refs[slot] -= 1

    def _pin(self, slot: int) -> None:
        self.slot_refs[slot] += 1
        self._tick += 1
        self._slot_touch[slot] = self._tick

"""Multi-host engine stepping: one scheduler, N SPMD participants.

In multi-controller JAX every process must enter the same jitted
computation for its collectives to match (the partitioner's ICI
all-reduces span all hosts). So the coordinator (pod 0) cannot just run
``Engine.step()`` by itself while followers idle — followers would never
enter the program and the slice would deadlock.

Protocol (the TPU-native stand-in for the reference's NCCL rendezvous,
SURVEY §2.4 / §5 "Distributed communication backend"):

- The scheduler (admission, page allocation, sampling-parameter tables)
  runs ONLY on the coordinator; it is plain host Python.
- Before every device step, the coordinator broadcasts a fixed-size int32
  HEADER [op, bucket, batch] then the step's host inputs; followers mirror
  the broadcast, materialize the same global arrays, and enter the same
  jitted function. Payload shapes are derivable from the header alone, so
  followers never need scheduler state.
- op codes: 0 = idle tick (followers wait again), 1 = prefill(bucket),
  2 = decode, 3 = shutdown.

``multihost_utils.broadcast_one_to_all`` carries the payload (psum under
the hood over DCN/ICI).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

OP_IDLE = 0
OP_PREFILL = 1
OP_DECODE = 2
OP_SHUTDOWN = 3
OP_CHUNK = 4  # chunked prefill: prefill payload + per-row history offsets

HEADER_LEN = 3  # [op, bucket, batch]


def _broadcast(value):
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def broadcast_header(op: int, bucket: int = 0, batch: int = 0) -> np.ndarray:
    hdr = np.asarray([op, bucket, batch], np.int32)
    return np.asarray(_broadcast(hdr))


def _payload_struct(op: int, bucket: int, batch: int, pages_per_seq: int):
    """Shapes of the host-side step inputs, derivable from the header."""
    if op in (OP_PREFILL, OP_CHUNK):
        struct = {
            "tokens": np.zeros((batch, bucket), np.int32),
            "lengths": np.zeros((batch,), np.int32),
            "page_table": np.zeros((batch, pages_per_seq), np.int32),
            "seeds": np.zeros((batch,), np.int32),
            "temps": np.zeros((batch,), np.float32),
            "top_ks": np.zeros((batch,), np.int32),
            "top_ps": np.zeros((batch,), np.float32),
        }
        if op == OP_CHUNK:
            struct["history"] = np.zeros((batch,), np.int32)
        return struct
    if op == OP_DECODE:
        return {
            "tokens": np.zeros((batch,), np.int32),
            "lengths": np.zeros((batch,), np.int32),
            "page_table": np.zeros((batch, pages_per_seq), np.int32),
            "seeds": np.zeros((batch,), np.int32),
            "temps": np.zeros((batch,), np.float32),
            "top_ks": np.zeros((batch,), np.int32),
            "top_ps": np.zeros((batch,), np.float32),
        }
    raise ValueError(f"op {op} carries no payload")


def broadcast_payload(payload: Optional[dict], op: int, bucket: int,
                      batch: int, pages_per_seq: int) -> dict:
    """Coordinator passes the real payload; followers pass None and get the
    coordinator's values back (broadcast ignores non-zero-process input)."""
    if payload is None:
        payload = _payload_struct(op, bucket, batch, pages_per_seq)
    out = _broadcast(payload)
    return {k: np.asarray(v) for k, v in out.items()}


def follower_loop(engine: Any) -> None:
    """Run on pods 1..N-1: mirror the coordinator's step sequence forever.

    The engine instance holds the sharded params/cache (global arrays whose
    addressable shards live on this host's chips) and the same jitted
    step functions; this loop feeds them the broadcast inputs.
    """
    import jax
    import jax.numpy as jnp

    pps = engine.config.pages_per_slot
    while True:
        hdr = broadcast_header(OP_IDLE)  # actually receives coordinator's hdr
        op, bucket, batch = int(hdr[0]), int(hdr[1]), int(hdr[2])
        if op == OP_SHUTDOWN:
            return
        if op == OP_IDLE:
            continue
        p = broadcast_payload(None, op, bucket, batch, pps)
        args = (
            engine.params, engine.model_config, jnp.asarray(p["tokens"]),
            jnp.asarray(p["lengths"]), engine.k_pages, engine.v_pages,
            jnp.asarray(p["page_table"]), engine._key,
            jnp.asarray(p["seeds"]), jnp.asarray(p["temps"]),
            jnp.asarray(p["top_ks"]), jnp.asarray(p["top_ps"]),
        )
        if op == OP_PREFILL:
            _t, _l, engine.k_pages, engine.v_pages = engine._prefill(*args)
        elif op == OP_CHUNK:
            _t, _l, engine.k_pages, engine.v_pages = engine._chunk(
                *args, jnp.asarray(p["history"])
            )
        else:
            _t, _l, engine.k_pages, engine.v_pages = engine._decode(*args)

"""Multi-host engine stepping: one scheduler, N SPMD participants.

In multi-controller JAX every process must enter the same jitted
computation for its collectives to match (the partitioner's ICI
all-reduces span all hosts). So the coordinator (pod 0) cannot just run
``Engine.step()`` by itself while followers idle — followers would never
enter the program and the slice would deadlock.

Packed protocol (v2 — the TPU-native stand-in for the reference's NCCL
rendezvous, SURVEY §2.4 / §5 "Distributed communication backend"):

- The scheduler (admission, page allocation, sampling-parameter tables)
  runs ONLY on the coordinator; it is plain host Python.
- Every device call is announced by exactly ONE
  ``multihost_utils.broadcast_one_to_all`` of a fixed-shape int32 message
  (control word + the same packed arrays the single-host engine already
  builds for its packed executables). One broadcast = one DCN/ICI round
  per step — the old header+payload protocol paid two.
- ASYNC scheduling works across hosts: the decode input merge happens on
  device from the previous step's sampled tokens, so followers never need
  host values — they mirror the coordinator's call sequence and keep
  references to their own ``last_toks`` / ``prefill_toks`` outputs, which
  are the same global arrays by SPMD determinism. The control word's
  ``last_valid`` / ``use_prefill`` bits tell them which reference the
  coordinator wired into the merge.
- Followers do no host reads and no allocation: page tables, lengths, and
  sampling parameters all ride inside the packed arrays.

Message layout (all int32; floats ride bitcast, as in the packed steps):

  ctrl[8]    = [op, k_rows, bucket, last_valid, use_prefill, fsm_used,
                score_width, score_len]
  pre_tokens [admit_batch, max_bucket]   prefill/chunk token ids
  pre_packed [admit_batch, _CHK_COLS + pages_per_slot]
  dec_packed [max_decode_slots, _DEC_COLS + pages_per_slot]

Unused fields are zero; the buffers are small (tens of KB) next to a
step's compute, and a single fixed pytree keeps the broadcast one
compiled executable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

MSG_IDLE = 0      # follower receive stub only; the coordinator never sends it
MSG_PREFILL = 1
MSG_CHUNK = 2
MSG_DECODE = 3
MSG_SHUTDOWN = 4
# multimodal prefill: the control word announces it (tokens/packed ride the
# normal buffers), then ONE extra broadcast carries the pixel payload +
# mrope positions (engine._mm_execute runs identically on every process) —
# the common decode/prefill path stays a single broadcast
MSG_MM_PREFILL = 5
# grammar residency change: the control word announces it, then ONE extra
# broadcast ships the updated device tables (engine/grammar.py) — like the
# multimodal pixel payload, the common step path stays a single broadcast.
# Sent only when the resident-grammar SET changes (admission-time).
MSG_GRAMMAR = 6
# prompt scoring (echo+logprobs): the control word carries the padded
# width and true length in ctrl[6:8], then ONE extra broadcast ships the
# [1, width] token row (width can exceed max_bucket — scoring pads to a
# multiple of the largest bucket — so it can't ride pre_tokens). Followers
# enter the same forward_score executable and discard the result.
MSG_SCORE = 7

CTRL_LEN = 8


@dataclasses.dataclass(frozen=True)
class ProtoShapes:
    """Fixed message-buffer shapes, derivable from the engine + model
    configs on every process (both are part of the deployment spec,
    identical per pod)."""
    admit_batch: int
    max_bucket: int
    pre_width: int     # _CHK_COLS + pages_per_slot (covers prefill's too)
    num_slots: int
    dec_width: int     # _DEC_COLS + pages_per_slot
    # multimodal payload (0s when the model has no vision tower): every
    # dynamic-resolution grid holds the same pixel COUNT (fixed patch
    # budget), so images broadcast as flat fixed-size rows + their grids
    n_img_max: int = 0
    img_floats: int = 0   # pixels per image row: S^2 * p^2 * C
    mrope: bool = False
    # frames per pixel-buffer row: a video temporal patch is
    # temporal_patch_size real frames; one row holds exactly one image OR
    # one temporal patch, so total rows <= total blocks <= n_img_max
    mm_row_frames: int = 2
    # grammar device-table shapes (EngineConfig caps + model vocab);
    # only the MSG_GRAMMAR payload broadcast uses them
    g_rows: int = 0
    g_vocab: int = 0
    g_states: int = 0
    g_classes: int = 0

    @classmethod
    def from_engine_config(cls, cfg: Any,
                           model_config: Any = None) -> "ProtoShapes":
        from llms_on_kubernetes_tpu.engine.engine import _CHK_COLS, _DEC_COLS

        n_img = img_floats = 0
        mrope = False
        row_frames = 2
        vocab = 0
        if model_config is not None:
            vocab = model_config.vocab_size
            if model_config.vision is not None:
                v = model_config.vision
                n_img = cfg.max_images_per_request
                img_floats = v.image_size * v.image_size * v.num_channels
                mrope = model_config.mrope_section is not None
                row_frames = max(1, v.temporal_patch_size)
        return cls(
            admit_batch=cfg.admit_batch,
            max_bucket=max(cfg.prefill_buckets),
            pre_width=_CHK_COLS + cfg.pages_per_slot,
            num_slots=cfg.max_decode_slots,
            dec_width=_DEC_COLS + cfg.pages_per_slot,
            n_img_max=n_img, img_floats=img_floats, mrope=mrope,
            mm_row_frames=row_frames,
            g_rows=cfg.max_grammars, g_vocab=vocab,
            g_states=cfg.grammar_states, g_classes=cfg.grammar_classes,
        )

    def zeros(self) -> dict:
        return {
            "ctrl": np.zeros((CTRL_LEN,), np.int32),
            "pre_tokens": np.zeros((self.admit_batch, self.max_bucket), np.int32),
            "pre_packed": np.zeros((self.admit_batch, self.pre_width), np.int32),
            "dec_packed": np.zeros((self.num_slots, self.dec_width), np.int32),
        }

    def mm_zeros(self) -> dict:
        """The second (mm-only) broadcast: entry pixels flattened into
        block-aligned rows, per-entry (frames, H, W) shapes (frames=0 =>
        image), and the mrope position block."""
        return {
            "meta": np.zeros((1 + 3 * self.n_img_max,), np.int32),
            "pixels": np.zeros(
                (self.n_img_max, self.mm_row_frames * self.img_floats),
                np.float32),
            "pos3": np.zeros((3, self.max_bucket), np.int32),
        }

    def grammar_zeros(self) -> dict:
        """The second (MSG_GRAMMAR-only) broadcast: the full host-side
        grammar tables (int16 — a few MB at the default caps, sent only
        when the resident set changes)."""
        return {
            "class_of": np.zeros((self.g_rows, self.g_vocab), np.int16),
            "trans": np.zeros((self.g_states, self.g_classes), np.int16),
        }


def _broadcast(value):
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


def send_message(
    shapes: ProtoShapes,
    op: int,
    *,
    pre_tokens: Optional[np.ndarray] = None,
    pre_packed: Optional[np.ndarray] = None,
    dec_packed: Optional[np.ndarray] = None,
    last_valid: bool = False,
    use_prefill: bool = False,
    fsm_used: bool = False,
    score: "Optional[tuple[int, int]]" = None,
) -> None:
    """Coordinator: announce one device call in ONE broadcast.
    ``fsm_used`` tells followers to enter the grammar-constrained variant
    of the step executable (same trace decision as the coordinator).
    ``score`` = (padded width, true length) for MSG_SCORE — the payload
    broadcast that follows is shaped from the width."""
    msg = shapes.zeros()
    k = bucket = 0
    if pre_tokens is not None:
        k, bucket = pre_tokens.shape
        msg["pre_tokens"][:k, :bucket] = pre_tokens
        msg["pre_packed"][:k, :pre_packed.shape[1]] = pre_packed
    if dec_packed is not None:
        msg["dec_packed"][:, :] = dec_packed
    msg["ctrl"][:6] = (op, k, bucket, int(last_valid), int(use_prefill),
                       int(fsm_used))
    if score is not None:
        msg["ctrl"][6:8] = score
    _broadcast(msg)


def receive_message(shapes: ProtoShapes) -> dict:
    """Follower: contribute zeros, receive the coordinator's message."""
    out = _broadcast(shapes.zeros())
    return {k: np.asarray(v) for k, v in out.items()}


def send_mm_payload(shapes: ProtoShapes, images: list,
                    pos3: "Optional[np.ndarray]") -> None:
    """Coordinator: ship a multimodal admission's pixels (+ mrope
    positions) in one broadcast right after its MSG_MM_PREFILL control.
    Entries are images [H, W, C] (meta frames=0) or videos [F, H, W, C];
    a video occupies F / mm_row_frames consecutive block-aligned rows."""
    msg = shapes.mm_zeros()
    msg["meta"][0] = len(images)
    row = 0
    for i, im in enumerate(images):
        video = im.ndim == 4
        f, h, w = (im.shape[0] if video else 0), im.shape[-3], im.shape[-2]
        msg["meta"][1 + 3 * i:4 + 3 * i] = (f, h, w)
        flat = np.asarray(im, np.float32).reshape(-1)
        n_rows = max(1, f // shapes.mm_row_frames)
        msg["pixels"][row:row + n_rows].reshape(-1)[:flat.size] = flat
        row += n_rows
    if pos3 is not None:
        msg["pos3"][:, :pos3.shape[-1]] = pos3
    _broadcast(msg)


def receive_mm_payload(shapes: ProtoShapes, channels: int,
                       bucket: int) -> "tuple[list, Optional[np.ndarray]]":
    """Follower: rebuild the admission's image/video list (per-entry
    dynamic grids) and the [3, bucket] mrope block (None for non-mrope
    models)."""
    out = _broadcast(shapes.mm_zeros())
    meta = np.asarray(out["meta"])
    pixels = np.asarray(out["pixels"])
    images = []
    row = 0
    for i in range(int(meta[0])):
        f, h, w = (int(x) for x in meta[1 + 3 * i:4 + 3 * i])
        if f:  # video
            n_rows = f // shapes.mm_row_frames
            flat = pixels[row:row + n_rows].reshape(-1)[:f * h * w * channels]
            images.append(flat.reshape(f, h, w, channels))
            row += n_rows
        else:
            images.append(
                pixels[row, :h * w * channels].reshape(h, w, channels))
            row += 1
    pos3 = np.asarray(out["pos3"])[:, :bucket] if shapes.mrope else None
    return images, pos3


def send_score_payload(tokens: np.ndarray) -> None:
    """Coordinator: ship the padded [1, width] score-token row right
    after its MSG_SCORE control word."""
    _broadcast(np.asarray(tokens, np.int32))


def receive_score_payload(width: int) -> np.ndarray:
    """Follower: receive the [1, width] token row (width from ctrl[6])."""
    return np.asarray(_broadcast(np.zeros((1, width), np.int32)))


def send_grammar_payload(shapes: ProtoShapes, class_h: np.ndarray,
                         trans_h: np.ndarray) -> None:
    """Coordinator: ship the full grammar tables right after MSG_GRAMMAR."""
    msg = shapes.grammar_zeros()
    msg["class_of"][:, :] = class_h
    msg["trans"][:, :] = trans_h
    _broadcast(msg)


def receive_grammar_payload(shapes: ProtoShapes) -> dict:
    out = _broadcast(shapes.grammar_zeros())
    return {k: np.asarray(v) for k, v in out.items()}


def follower_loop(engine: Any) -> None:
    """Run on pods 1..N-1: mirror the coordinator's call sequence forever.

    The engine instance holds the sharded params/cache (global arrays whose
    addressable shards live on this host's chips) and the same jitted
    packed executables; this loop feeds them the broadcast inputs. By SPMD
    determinism the follower's ``last_toks``/``prefill_toks`` outputs are
    the same global arrays the coordinator wired into its decode merges.
    """
    import jax.numpy as jnp

    from llms_on_kubernetes_tpu.engine.cache import set_kv_write_strategy
    from llms_on_kubernetes_tpu.engine.engine import _CHK_COLS, _DEC_COLS, _PRE_COLS

    # the follower traces the same executables this loop feeds; pin the
    # trace-time context to THIS engine's config (engine.step does the
    # same on the coordinator)
    set_kv_write_strategy(engine.config.kv_write)
    shapes = ProtoShapes.from_engine_config(engine.config,
                                            engine.model_config)
    pps = engine.config.pages_per_slot
    last_toks = engine._zeros_B
    prefill_toks = engine._zeros_1
    while True:
        m = receive_message(shapes)
        op, k, bucket, last_valid, use_prefill, fsm_used = (
            int(x) for x in m["ctrl"][:6])
        if op == MSG_SHUTDOWN:
            return
        if op == MSG_IDLE:
            continue
        if op == MSG_GRAMMAR:
            # mirror the coordinator's residency change: same host tables,
            # same device arrays (engine._ensure_grammar/_upload_grammars)
            payload = receive_grammar_payload(shapes)
            engine._g_class_h = payload["class_of"]
            engine._g_trans_h = payload["trans"]
            if engine._fsm_state is None:
                engine._fsm_state = jnp.full(
                    (engine.config.max_decode_slots,), -1, jnp.int32)
            engine._g_dev = (jnp.asarray(engine._g_class_h),
                             jnp.asarray(engine._g_trans_h))
            continue
        if op == MSG_SCORE:
            # mirror the coordinator's forward_score entry (cache-free,
            # trash-pool writes) and discard the result — SPMD only needs
            # every process inside the same executable
            from llms_on_kubernetes_tpu.engine.sampling import LOGPROB_TOPK

            width, n = int(m["ctrl"][6]), int(m["ctrl"][7])
            toks = receive_score_payload(width)
            engine._score_jit(engine.params, engine.model_config,
                              jnp.asarray(toks), jnp.asarray([n], jnp.int32),
                              LOGPROB_TOPK)
            continue
        if op == MSG_MM_PREFILL:
            images, pos3 = receive_mm_payload(
                shapes, engine.model_config.vision.num_channels, bucket)
            _pack, toks = engine._mm_execute(
                images, m["pre_tokens"][:k, :bucket],
                m["pre_packed"][:k, :_PRE_COLS + pps],
                None if pos3 is None else pos3[None])
            prefill_toks = toks
            continue
        fsm = engine._fsm_args() if fsm_used else None
        if op in (MSG_PREFILL, MSG_CHUNK):
            cols = (_PRE_COLS if op == MSG_PREFILL else _CHK_COLS) + pps
            tokens = jnp.asarray(m["pre_tokens"][:k, :bucket])
            packed = jnp.asarray(m["pre_packed"][:k, :cols])
            fn = engine._prefill_packed if op == MSG_PREFILL else engine._chunk_packed
            (_pack, toks, engine.k_pages, engine.v_pages,
             engine.token_counts, new_state) = fn(
                engine.params, engine.model_config, tokens, packed,
                engine.k_pages, engine.v_pages, engine.token_counts,
                engine._key, fsm,
            )
            if new_state is not None:
                engine._fsm_state = new_state
            prefill_toks = toks
        elif op == MSG_DECODE:
            packed = jnp.asarray(m["dec_packed"])
            last = last_toks if last_valid else engine._zeros_B
            pre = prefill_toks if use_prefill else engine._zeros_1
            (_pack, toks, engine.k_pages, engine.v_pages,
             engine.token_counts, new_state) = engine._decode_packed(
                engine.params, engine.model_config, packed, last, pre,
                engine.k_pages, engine.v_pages, engine.token_counts,
                engine._key, fsm,
            )
            if new_state is not None:
                engine._fsm_state = new_state
            last_toks = toks
        else:
            raise ValueError(f"unknown multihost op {op}")

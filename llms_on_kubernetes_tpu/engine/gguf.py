"""GGUF checkpoint loading: llama.cpp model files → engine params.

The reference's local solution serves GGUF files with ``llama-server
--model <modelPath>`` (reference ramalama-models/helm-chart/
templates/model-deployments.yaml:26-35; values.yaml ``modelPath``), with
TinyLlama Q8_0 and Phi-3-mini q4 as the documented models
(ramalama-models/README.md:96-107). This module gives the TPU-native
engine the same input format: parse the GGUF container, dequantize the
ggml-quantized tensors to numpy, and map llama.cpp tensor names into the
decoder's layer-stacked layout — so a ``models[].modelPath`` pointing at
a GGUF file works against this engine exactly as it did against
llama.cpp (weights-wise; tokenization runs through the usual tokenizer).

Implemented ggml dtypes: F32, F16, Q8_0, Q4_0, Q4_1, Q4_K, Q6_K — the
formats the reference's two documented models (plus their output heads,
which llama.cpp keeps in Q6_K for q4 files) actually use.

Format reference: the public GGUF spec (magic "GGUF", little-endian,
v2/v3 headers; metadata KV section; tensor infos; alignment-padded data
section). Dequantization layouts follow the public ggml block formats.
"""

from __future__ import annotations

import mmap
import struct
from typing import Any, BinaryIO, Optional

import numpy as np

from llms_on_kubernetes_tpu.configs import ModelConfig

Params = dict[str, Any]

GGUF_MAGIC = b"GGUF"

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL = range(8)
_T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = range(8, 13)

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_BOOL: "<?",
    _T_U64: "<Q", _T_I64: "<q", _T_F64: "<d",
}

# ggml tensor dtypes (subset)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q8_0 = 8
GGML_Q4_K = 12
GGML_Q6_K = 14

_QK = 32       # elements per simple-quant block
_QK_K = 256    # elements per k-quant super-block


def _block_layout(ggml_type: int) -> tuple[int, int]:
    """(elements per block, bytes per block)."""
    if ggml_type == GGML_F32:
        return 1, 4
    if ggml_type == GGML_F16:
        return 1, 2
    if ggml_type == GGML_Q8_0:
        return _QK, 2 + _QK                       # f16 d + 32 x i8
    if ggml_type == GGML_Q4_0:
        return _QK, 2 + _QK // 2                  # f16 d + 16 bytes nibbles
    if ggml_type == GGML_Q4_1:
        return _QK, 4 + _QK // 2                  # f16 d, f16 m + nibbles
    if ggml_type == GGML_Q4_K:
        return _QK_K, 2 + 2 + 12 + _QK_K // 2     # d, dmin, scales, qs
    if ggml_type == GGML_Q6_K:
        return _QK_K, _QK_K // 2 + _QK_K // 4 + _QK_K // 16 + 2
    raise NotImplementedError(f"ggml tensor type {ggml_type} not supported")


# ---------------------------------------------------------------------------
# Dequantization (vectorized numpy, one call per tensor)
# ---------------------------------------------------------------------------

def _dequant_q8_0(raw: np.ndarray, n: int) -> np.ndarray:
    blocks = raw.reshape(-1, 2 + _QK)
    d = blocks[:, :2].copy().view(np.float16).astype(np.float32)   # [B,1]
    q = blocks[:, 2:].view(np.int8).astype(np.float32)             # [B,32]
    return (d * q).reshape(-1)[:n]


def _dequant_q4_0(raw: np.ndarray, n: int) -> np.ndarray:
    blocks = raw.reshape(-1, 2 + _QK // 2)
    d = blocks[:, :2].copy().view(np.float16).astype(np.float32)
    qs = blocks[:, 2:]
    lo = (qs & 0x0F).astype(np.float32) - 8.0                      # [B,16]
    hi = (qs >> 4).astype(np.float32) - 8.0
    out = np.concatenate([lo, hi], axis=1) * d                     # [B,32]
    return out.reshape(-1)[:n]


def _dequant_q4_1(raw: np.ndarray, n: int) -> np.ndarray:
    blocks = raw.reshape(-1, 4 + _QK // 2)
    d = blocks[:, :2].copy().view(np.float16).astype(np.float32)
    m = blocks[:, 2:4].copy().view(np.float16).astype(np.float32)
    qs = blocks[:, 4:]
    lo = (qs & 0x0F).astype(np.float32)
    hi = (qs >> 4).astype(np.float32)
    out = np.concatenate([lo, hi], axis=1) * d + m
    return out.reshape(-1)[:n]


def _unpack_qk_scales(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Q4_K packed 6-bit scales/mins: scales[12] bytes -> (sc[8], m[8])."""
    sc = np.empty(scales.shape[:-1] + (8,), np.float32)
    mn = np.empty_like(sc)
    s = scales.astype(np.uint8)
    for j in range(8):
        if j < 4:
            sc[..., j] = (s[..., j] & 63)
            mn[..., j] = (s[..., j + 4] & 63)
        else:
            sc[..., j] = (s[..., j + 4] & 0x0F) | ((s[..., j - 4] >> 6) << 4)
            mn[..., j] = (s[..., j + 4] >> 4) | ((s[..., j] >> 6) << 4)
    return sc, mn


def _dequant_q4_k(raw: np.ndarray, n: int) -> np.ndarray:
    bs = 2 + 2 + 12 + _QK_K // 2
    blocks = raw.reshape(-1, bs)
    d = blocks[:, 0:2].copy().view(np.float16).astype(np.float32)[:, 0]
    dmin = blocks[:, 2:4].copy().view(np.float16).astype(np.float32)[:, 0]
    sc, mn = _unpack_qk_scales(blocks[:, 4:16])                    # [B,8]
    qs = blocks[:, 16:]                                            # [B,128]
    B = blocks.shape[0]
    out = np.empty((B, _QK_K), np.float32)
    # 4 chunks of 64 elements; chunk c uses qs[32c:32c+32]: low nibbles are
    # sub-block 2c, high nibbles sub-block 2c+1
    for c in range(4):
        q = qs[:, 32 * c:32 * (c + 1)]
        lo = (q & 0x0F).astype(np.float32)
        hi = (q >> 4).astype(np.float32)
        dlo = d * sc[:, 2 * c]
        dhi = d * sc[:, 2 * c + 1]
        mlo = dmin * mn[:, 2 * c]
        mhi = dmin * mn[:, 2 * c + 1]
        out[:, 64 * c:64 * c + 32] = dlo[:, None] * lo - mlo[:, None]
        out[:, 64 * c + 32:64 * (c + 1)] = dhi[:, None] * hi - mhi[:, None]
    return out.reshape(-1)[:n]


def _dequant_q6_k(raw: np.ndarray, n: int) -> np.ndarray:
    bs = _QK_K // 2 + _QK_K // 4 + _QK_K // 16 + 2
    blocks = raw.reshape(-1, bs)
    ql = blocks[:, :128]
    qh = blocks[:, 128:192]
    scales = blocks[:, 192:208].view(np.int8).astype(np.float32)   # [B,16]
    d = blocks[:, 208:210].copy().view(np.float16).astype(np.float32)[:, 0]
    B = blocks.shape[0]
    out = np.empty((B, _QK_K), np.float32)
    # two 128-element halves; each half: ql[64h:64h+64], qh[32h:32h+32]
    for half in range(2):
        l = ql[:, 64 * half:64 * (half + 1)]                       # [B,64]
        h = qh[:, 32 * half:32 * (half + 1)]                       # [B,32]
        q1 = (l[:, :32] & 0x0F) | ((h & 0x03) << 4)
        q2 = (l[:, 32:] & 0x0F) | (((h >> 2) & 0x03) << 4)
        q3 = (l[:, :32] >> 4) | (((h >> 4) & 0x03) << 4)
        q4 = (l[:, 32:] >> 4) | (((h >> 6) & 0x03) << 4)
        qq = np.concatenate([q1, q2, q3, q4], axis=1).astype(np.float32) - 32.0
        base = 128 * half
        for sub in range(4):  # 4 sub-blocks of 32 within the half
            sidx = 8 * half + 2 * sub  # scale index step: 16 scales per 256
            # scales are per 16 elements: elements [32*sub, 32*sub+32) use
            # scales[8h + 2sub] and [8h + 2sub + 1]
            seg = qq[:, 32 * sub:32 * (sub + 1)]
            out[:, base + 32 * sub:base + 32 * sub + 16] = (
                d[:, None] * scales[:, [sidx]] * seg[:, :16])
            out[:, base + 32 * sub + 16:base + 32 * (sub + 1)] = (
                d[:, None] * scales[:, [sidx + 1]] * seg[:, 16:])
    return out.reshape(-1)[:n]


def dequantize(ggml_type: int, raw: np.ndarray, n_elements: int) -> np.ndarray:
    """raw uint8 buffer for a whole tensor -> float32 [n_elements]."""
    if ggml_type == GGML_F32:
        return raw.view(np.float32)[:n_elements].astype(np.float32)
    if ggml_type == GGML_F16:
        return raw.view(np.float16)[:n_elements].astype(np.float32)
    if ggml_type == GGML_Q8_0:
        return _dequant_q8_0(raw, n_elements)
    if ggml_type == GGML_Q4_0:
        return _dequant_q4_0(raw, n_elements)
    if ggml_type == GGML_Q4_1:
        return _dequant_q4_1(raw, n_elements)
    if ggml_type == GGML_Q4_K:
        return _dequant_q4_k(raw, n_elements)
    if ggml_type == GGML_Q6_K:
        return _dequant_q6_k(raw, n_elements)
    raise NotImplementedError(f"ggml tensor type {ggml_type} not supported")


# ---------------------------------------------------------------------------
# Container parsing
# ---------------------------------------------------------------------------

class GGUFFile:
    """Parsed GGUF container: metadata dict + lazy mmap tensor access."""

    def __init__(self, path: str):
        self.path = str(path)
        self.metadata: dict[str, Any] = {}
        # name -> (shape tuple (row-major, out-first), ggml_type, offset, n)
        self.tensors: dict[str, tuple[tuple[int, ...], int, int, int]] = {}
        self._file: Optional[BinaryIO] = open(self.path, "rb")
        self._parse(self._file)
        self._mm = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)

    # -- reading helpers -------------------------------------------------
    @staticmethod
    def _read(f: BinaryIO, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, f.read(size))[0]

    def _read_string(self, f: BinaryIO) -> str:
        n = self._read(f, "<Q")
        return f.read(n).decode("utf-8", errors="replace")

    def _read_value(self, f: BinaryIO, vtype: int):
        if vtype in _SCALAR_FMT:
            return self._read(f, _SCALAR_FMT[vtype])
        if vtype == _T_STRING:
            return self._read_string(f)
        if vtype == _T_ARRAY:
            etype = self._read(f, "<I")
            count = self._read(f, "<Q")
            return [self._read_value(f, etype) for _ in range(count)]
        raise ValueError(f"bad GGUF metadata value type {vtype}")

    def _parse(self, f: BinaryIO) -> None:
        if f.read(4) != GGUF_MAGIC:
            raise ValueError(f"{self.path}: not a GGUF file")
        version = self._read(f, "<I")
        if version < 2:
            raise ValueError(f"GGUF v{version} unsupported (need v2+)")
        n_tensors = self._read(f, "<Q")
        n_kv = self._read(f, "<Q")
        for _ in range(n_kv):
            key = self._read_string(f)
            vtype = self._read(f, "<I")
            self.metadata[key] = self._read_value(f, vtype)

        infos = []
        for _ in range(n_tensors):
            name = self._read_string(f)
            n_dims = self._read(f, "<I")
            # GGUF stores ne[] fastest-varying first; reverse for row-major
            ne = [self._read(f, "<Q") for _ in range(n_dims)]
            ggml_type = self._read(f, "<I")
            offset = self._read(f, "<Q")
            infos.append((name, tuple(reversed(ne)), ggml_type, offset))

        alignment = int(self.metadata.get("general.alignment", 32))
        data_start = f.tell()
        data_start += (-data_start) % alignment
        self._data_start = data_start
        for name, shape, ggml_type, offset in infos:
            n = int(np.prod(shape)) if shape else 1
            self.tensors[name] = (shape, ggml_type, data_start + offset, n)

    # -- tensor access ---------------------------------------------------
    def tensor(self, name: str) -> np.ndarray:
        """Dequantized float32 tensor in row-major [out, in] orientation."""
        shape, ggml_type, offset, n = self.tensors[name]
        elems, bpb = _block_layout(ggml_type)
        nbytes = (n // elems) * bpb
        raw = np.frombuffer(self._mm, np.uint8, count=nbytes, offset=offset)
        return dequantize(ggml_type, raw, n).reshape(shape)

    def close(self) -> None:
        if self._file is not None:
            self._mm.close()
            self._file.close()
            self._file = None


# ---------------------------------------------------------------------------
# Metadata -> ModelConfig
# ---------------------------------------------------------------------------

def config_from_gguf(gf: GGUFFile, name: Optional[str] = None) -> ModelConfig:
    md = gf.metadata
    arch = md.get("general.architecture", "llama")
    g = lambda k, default=None: md.get(f"{arch}.{k}", default)  # noqa: E731

    D = int(g("embedding_length"))
    H = int(g("attention.head_count"))
    KV = int(g("attention.head_count_kv", H))
    hd = int(g("attention.key_length", D // H))
    vocab = gf.tensors["token_embd.weight"][0][0]
    return ModelConfig(
        name=name or md.get("general.name", arch),
        vocab_size=int(vocab),
        hidden_size=D,
        intermediate_size=int(g("feed_forward_length")),
        num_layers=int(g("block_count")),
        num_heads=H,
        num_kv_heads=KV,
        head_dim=hd,
        rope_theta=float(g("rope.freq_base", 10000.0)),
        rms_norm_eps=float(g("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position_embeddings=int(g("context_length", 4096)),
        tie_word_embeddings="output.weight" not in gf.tensors,
        sliding_window=(int(g("attention.sliding_window"))
                        if g("attention.sliding_window") else None),
    )


# ---------------------------------------------------------------------------
# Tensor-name mapping -> engine params
# ---------------------------------------------------------------------------

def load_gguf_params(
    path: "str | GGUFFile",
    cfg: Optional[ModelConfig] = None,
    dtype: Optional[str] = None,
    quantization: Optional[str] = None,
    mesh=None,
):
    """Load a .gguf file -> (ModelConfig, params) in the decoder layout.

    llama.cpp name schema: token_embd / output_norm / output and
    blk.{i}.{attn_q,attn_k,attn_v,attn_output,attn_norm,ffn_gate,ffn_up,
    ffn_down,ffn_norm}[.weight], with fused blk.{i}.attn_qkv for phi3-style
    exports. GGUF stores linears as [out, in] (after ne-reversal), same as
    HF — the same transpose rules as weights.py apply.
    """
    import jax
    import jax.numpy as jnp

    gf = path if isinstance(path, GGUFFile) else GGUFFile(path)
    cfg = cfg or config_from_gguf(gf)
    dt = jnp.dtype(dtype or cfg.dtype)
    H, KV, hd, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.hidden_size

    def lin(name: str, out_reshape=None) -> np.ndarray:
        w = gf.tensor(name).T  # [in, out]
        if out_reshape is not None:
            w = w.reshape(w.shape[0], *out_reshape)
        return w

    per_layer: list[Params] = []
    for i in range(cfg.num_layers):
        p = f"blk.{i}."
        lp: Params = {}
        if p + "attn_q.weight" in gf.tensors:
            lp["wq"] = lin(p + "attn_q.weight", (H, hd))
            lp["wk"] = lin(p + "attn_k.weight", (KV, hd))
            lp["wv"] = lin(p + "attn_v.weight", (KV, hd))
        else:  # fused qkv (phi3-style exports)
            qkv = gf.tensor(p + "attn_qkv.weight")  # [(H+2KV)*hd, D]
            q, k, v = np.split(qkv, [H * hd, (H + KV) * hd], axis=0)
            lp["wq"] = q.T.reshape(D, H, hd)
            lp["wk"] = k.T.reshape(D, KV, hd)
            lp["wv"] = v.T.reshape(D, KV, hd)
        lp["wo"] = gf.tensor(p + "attn_output.weight").T.reshape(H, hd, D)
        lp["attn_norm"] = gf.tensor(p + "attn_norm.weight")
        lp["mlp_norm"] = gf.tensor(p + "ffn_norm.weight")
        if p + "ffn_gate.weight" in gf.tensors:
            lp["w_gate"] = lin(p + "ffn_gate.weight")
            lp["w_up"] = lin(p + "ffn_up.weight")
        else:  # fused gate+up (phi3-style)
            gu = gf.tensor(p + "ffn_up.weight")
            g_, u_ = np.split(gu, 2, axis=0)
            lp["w_gate"] = g_.T
            lp["w_up"] = u_.T
        lp["w_down"] = lin(p + "ffn_down.weight")
        per_layer.append(lp)

    layers = {
        k: np.stack([pl[k] for pl in per_layer]).astype(dt)
        for k in per_layer[0]
    }
    params: Params = {
        "embed": gf.tensor("token_embd.weight").astype(dt),
        "final_norm": gf.tensor("output_norm.weight").astype(dt),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = gf.tensor("output.weight").T.astype(dt)
    gf.close()

    from llms_on_kubernetes_tpu.ops.quant import SUPPORTED_QUANTIZATIONS, quantize_params

    if quantization not in SUPPORTED_QUANTIZATIONS:
        raise ValueError(f"unknown quantization {quantization!r}")
    if quantization == "int8":
        params = quantize_params(params)

    if mesh is not None:
        from llms_on_kubernetes_tpu.parallel.sharding import shard_params

        params = shard_params(params, cfg, mesh)
    else:
        params = jax.tree.map(jnp.asarray, params)
    return cfg, params

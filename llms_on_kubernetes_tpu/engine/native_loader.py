"""ctypes bridge to the native safetensors reader (native/loader/).

``open_native_safetensors(dir)`` returns the same name->lazy-loader dict
shape as the pure-Python path in weights.py, backed by libstload.so's
mmap + madvise + parallel-copy reads. Falls back to None when the shared
library is absent or unloadable (the Python ``safetensors`` package then
handles loading) — the native path is an accelerator, not a requirement.

Set LLMK_NATIVE_LOADER=0 to force the Python path.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
from typing import Callable, Optional

import numpy as np

_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
    "U64": np.uint64, "U32": np.uint32, "U16": np.uint16,
}


class UnsupportedDTypeError(ValueError):
    """A safetensors dtype this bridge cannot map to numpy; callers fall
    back to the Python safetensors reader for that tensor (round-2 review
    finding: an FP8 checkpoint previously crashed with a raw KeyError)."""


def _np_dtype(dtype_s: str) -> Optional[np.dtype]:
    if dtype_s in _DTYPES:
        return np.dtype(_DTYPES[dtype_s])
    import ml_dtypes  # ships with jax

    ext = {
        "BF16": ml_dtypes.bfloat16,
        # compressed-tensors FP8 checkpoints (the reference's default
        # gemma-3-27b-it-FP8-Dynamic, reference values.yaml:3)
        "F8_E4M3": ml_dtypes.float8_e4m3fn,
        "F8_E5M2": ml_dtypes.float8_e5m2,
    }
    if dtype_s in ext:
        return np.dtype(ext[dtype_s])
    return None


_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _find_lib() -> Optional[str]:
    override = os.environ.get("LLMK_NATIVE_LOADER_PATH")
    if override:
        return override if os.path.exists(override) else None
    root = pathlib.Path(__file__).resolve().parents[2]
    cand = root / "native" / "loader" / "libstload.so"
    return str(cand) if cand.exists() else None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("LLMK_NATIVE_LOADER", "1") == "0":
        return None
    path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.stl_open.restype = ctypes.c_void_p
    lib.stl_open.argtypes = [ctypes.c_char_p]
    lib.stl_error.restype = ctypes.c_char_p
    lib.stl_count.restype = ctypes.c_int64
    lib.stl_count.argtypes = [ctypes.c_void_p]
    lib.stl_name.restype = ctypes.c_char_p
    lib.stl_name.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.stl_info.restype = ctypes.c_int64
    lib.stl_info.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                             ctypes.POINTER(ctypes.c_int64)]
    lib.stl_read.restype = ctypes.c_int
    lib.stl_read.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_void_p, ctypes.c_int64]
    lib.stl_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class _NativeShards:
    """Owns the native handle; loaders close over it (kept alive by refs)."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._handle = handle

    def __del__(self):
        try:
            self._lib.stl_close(self._handle)
        except Exception:
            pass

    def names(self) -> list[str]:
        n = self._lib.stl_count(self._handle)
        return [self._lib.stl_name(self._handle, i).decode()
                for i in range(n)]

    def read(self, name: str) -> np.ndarray:
        dtype_buf = ctypes.create_string_buffer(16)
        shape = (ctypes.c_int64 * 8)()
        nbytes = ctypes.c_int64()
        ndim = self._lib.stl_info(self._handle, name.encode(), dtype_buf,
                                  shape, ctypes.byref(nbytes))
        if ndim < 0:
            raise KeyError(self._lib.stl_error().decode())
        dtype_s = dtype_buf.value.decode()
        np_dtype = _np_dtype(dtype_s)
        if np_dtype is None:
            raise UnsupportedDTypeError(
                f"tensor {name!r} has safetensors dtype {dtype_s!r} with no "
                f"numpy mapping")
        shp = tuple(shape[i] for i in range(ndim))
        out = np.empty(shp, np_dtype)
        assert out.nbytes == nbytes.value, (name, out.nbytes, nbytes.value)
        rc = self._lib.stl_read(
            self._handle, name.encode(),
            out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
        )
        if rc != 0:
            raise IOError(self._lib.stl_error().decode())
        return out


def open_native_safetensors(
    model_dir: str,
) -> Optional[dict[str, Callable[[], np.ndarray]]]:
    """name -> lazy loader dict via libstload, or None (use Python path)."""
    lib = _load_lib()
    if lib is None:
        return None
    handle = lib.stl_open(str(model_dir).encode())
    if not handle:
        return None  # e.g. no shards found; Python path raises the error
    shards = _NativeShards(lib, handle)
    return {
        name: (lambda s=shards, n=name: s.read(n))
        for name in shards.names()
    }

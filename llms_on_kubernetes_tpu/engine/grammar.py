"""Grammar-constrained decoding: JSON / JSON-schema token-level DFAs.

The reference's per-model servers are ``vllm/vllm-openai:v0.11.0``
(reference vllm-models/helm-chart/templates/model-deployments.yaml:21,
values.yaml:22-23), which serve OpenAI ``response_format``
(``json_object`` / ``json_schema``) and grammar-guaranteed ``tool_choice``
via guided decoding (xgrammar/outlines). This module is the TPU-native
equivalent, designed for an engine whose sampled tokens NEVER visit the
host between steps (the async pipeline feeds each step's on-device tokens
straight into the next launch — engine.py): the constraint must therefore
be a pure on-device function, not a host callback.

Pipeline (all host-side, at request admission; cached):

1. A JSON value grammar (or a JSON-schema instance of it) is built as a
   small regex-like AST over BYTES. JSON nesting is not regular, so
   object/array recursion is unrolled to a bounded depth (default
   ``DEFAULT_DEPTH``) — the standard FSM-serving tradeoff (outlines does
   the same). List repetition ``X (sep X)*`` shares ONE copy of ``X`` via
   a loop edge, so the unrolling is 2^depth small fragments, not 4^depth.
2. AST -> byte-level NFA (Thompson) -> DFA (subset construction over
   byte equivalence classes) -> minimized DFA.
3. The char DFA is composed with the tokenizer: for every DFA state
   reachable at a TOKEN boundary, run every vocab token's byte string
   through the DFA (vectorized over the vocab with numpy) giving a
   token-level transition table T[state, token] (-1 = token not allowed).
   EOS tokens are allowed exactly at accepting states and lead to an
   absorbing DONE state.
4. T's columns are compressed to token equivalence classes (tokens with
   identical behavior in every state share a column): the device arrays
   are ``class_of [V] int16`` and ``trans [S, C] int16``. Per decode
   step the engine computes ``nxt = trans[state, class_of]`` on device —
   one gather that yields BOTH the logit mask (``nxt >= 0``) and, for
   the sampled token, the next state. No host round trip, so constrained
   requests ride the async pipeline at full speed.

Unsupported JSON-schema constructs raise :class:`GrammarError` (the API
layer maps it to HTTP 400): ``$ref``, ``allOf``, ``not``,
``if``/``then``/``else``, ``patternProperties``, ``pattern``,
``contains``, ``dependentSchemas``, ``propertyNames``. Value-range
keywords a finite automaton cannot express (``minimum``/``maximum``/
``multipleOf``/``format``/``uniqueItems``) are accepted and ignored —
the grammar guarantees the TYPE SHAPE; range validation stays an
application concern (same stance as vLLM's default guided backend).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Optional, Sequence

import numpy as np

# bounded-depth unrolling for generic (schema-less) JSON values
DEFAULT_DEPTH = 6
# hard bounds turning pathological schemas into 400s instead of
# minutes-long compiles
MAX_REP = 256          # minLength/maxLength/minItems/maxItems cap
MAX_NFA_NODES = 300_000
MAX_DFA_STATES = 20_000

_WS = frozenset(b" \t\n\r")
_DIGITS = frozenset(b"0123456789")
_DIGITS19 = frozenset(b"123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")
# JSON string character, UTF-8-exact: ASCII printable except '"' and '\',
# plus well-formed multi-byte sequences (no overlongs, no surrogates).
# Byte-fallback vocabs (Llama-3 ships all 256 single-byte tokens) could
# otherwise be steered into emitting invalid UTF-8 that json.loads rejects.
_ASCII_CHAR = frozenset(range(0x20, 0x80)) - frozenset(b'"\\')
_CONT = frozenset(range(0x80, 0xC0))


class GrammarError(ValueError):
    """Unsupported or invalid grammar/schema (HTTP 400 at the API)."""


# ---------------------------------------------------------------------------
# regex-like AST over bytes
# ---------------------------------------------------------------------------
# nodes: ("lit", bytes) | ("cls", frozenset[int]) | ("seq", [ast...])
#      | ("alt", [ast...]) | ("star", ast) | ("opt", ast)
#      | ("seplist", item_ast, sep_ast)     # item (sep item)* — ONE item copy


def lit(s: "bytes | str"):
    return ("lit", s.encode("utf-8") if isinstance(s, str) else bytes(s))


def cls(chars: frozenset) -> tuple:
    return ("cls", frozenset(chars))


def seq(*parts) -> tuple:
    return ("seq", list(parts))


def alt(*parts) -> tuple:
    return ("alt", list(parts))


def star(x) -> tuple:
    return ("star", x)


def opt(x) -> tuple:
    return ("opt", x)


def seplist(item, sep) -> tuple:
    return ("seplist", item, sep)


def rep(x, lo: int, hi: Optional[int]) -> tuple:
    """lo..hi copies of x (hi=None => unbounded)."""
    if lo < 0 or (hi is not None and hi < lo):
        raise GrammarError(f"bad repetition bounds ({lo}, {hi})")
    if hi is not None and hi > MAX_REP:
        raise GrammarError(f"repetition bound {hi} exceeds {MAX_REP}")
    if lo > MAX_REP:
        raise GrammarError(f"repetition bound {lo} exceeds {MAX_REP}")
    parts = [x] * lo
    if hi is None:
        parts.append(star(x))
    else:
        parts.extend([opt(x)] * (hi - lo))
    return ("seq", parts)


# ---------------------------------------------------------------------------
# NFA construction (Thompson with eps edges; seplist builds a shared loop)
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset, int]]] = []

    def node(self) -> int:
        if len(self.eps) >= MAX_NFA_NODES:
            raise GrammarError(
                f"grammar too large (> {MAX_NFA_NODES} NFA nodes)")
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def add_eps(self, a: int, b: int) -> None:
        self.eps[a].append(b)

    def add_edge(self, a: int, chars: frozenset, b: int) -> None:
        if chars:
            self.edges[a].append((chars, b))

    def build(self, ast) -> tuple[int, int]:
        """Compile ``ast`` into fresh nodes; returns (start, end)."""
        kind = ast[0]
        if kind == "lit":
            s = self.node()
            cur = s
            for byte in ast[1]:
                nxt = self.node()
                self.add_edge(cur, frozenset((byte,)), nxt)
                cur = nxt
            return s, cur
        if kind == "cls":
            s, e = self.node(), self.node()
            self.add_edge(s, ast[1], e)
            return s, e
        if kind == "seq":
            s = self.node()
            cur = s
            for part in ast[1]:
                ps, pe = self.build(part)
                self.add_eps(cur, ps)
                cur = pe
            return s, cur
        if kind == "alt":
            s, e = self.node(), self.node()
            for part in ast[1]:
                ps, pe = self.build(part)
                self.add_eps(s, ps)
                self.add_eps(pe, e)
            return s, e
        if kind == "star":
            s, e = self.node(), self.node()
            ps, pe = self.build(ast[1])
            self.add_eps(s, ps)
            self.add_eps(s, e)
            self.add_eps(pe, ps)
            self.add_eps(pe, e)
            return s, e
        if kind == "opt":
            s, e = self.node(), self.node()
            ps, pe = self.build(ast[1])
            self.add_eps(s, ps)
            self.add_eps(pe, e)
            self.add_eps(s, e)
            return s, e
        if kind == "seplist":
            # item (sep item)* with ONE copy of item: loop back through sep
            s, e = self.node(), self.node()
            is_, ie = self.build(ast[1])
            ss, se = self.build(ast[2])
            self.add_eps(s, is_)
            self.add_eps(ie, e)
            self.add_eps(ie, ss)
            self.add_eps(se, is_)
            return s, e
        if kind == "members":
            # ordered object members with optional skips: the separator
            # belongs to the TRANSITION between two present members, so
            # "has one been emitted yet" is encoded as two parallel node
            # chains (A = none yet, B = at least one) — LINEAR in the
            # member count, vs the 2^n duplication a naive alternation
            # over "first present member" suffers.
            items, sep = ast[1], ast[2]
            n = len(items)
            A = [self.node() for _ in range(n + 1)]
            Bc = [self.node() for _ in range(n + 1)]
            for i, (required, frag) in enumerate(items):
                fs, fe = self.build(frag)          # A-path copy (first)
                self.add_eps(A[i], fs)
                self.add_eps(fe, Bc[i + 1])
                ss, se = self.build(sep)           # B-path copy (sep+frag)
                fs2, fe2 = self.build(frag)
                self.add_eps(Bc[i], ss)
                self.add_eps(se, fs2)
                self.add_eps(fe2, Bc[i + 1])
                if not required:
                    self.add_eps(A[i], A[i + 1])
                    self.add_eps(Bc[i], Bc[i + 1])
            end = self.node()
            self.add_eps(A[n], end)   # reachable only if nothing required
            self.add_eps(Bc[n], end)
            return A[0], end
        raise GrammarError(f"unknown AST node {kind!r}")


@dataclasses.dataclass
class CharDFA:
    """Minimized byte-level DFA. ``table[state, byte2class[byte]]`` is the
    next state (-1 = reject); ``accept[state]`` marks accepting states."""

    table: np.ndarray        # [S, K] int32
    accept: np.ndarray       # [S] bool
    byte2class: np.ndarray   # [256] int32
    start: int

    def matches(self, data: bytes) -> bool:
        s = self.start
        for b in data:
            s = int(self.table[s, self.byte2class[b]])
            if s < 0:
                return False
        return bool(self.accept[s])


def _byte_classes(nfa: _NFA) -> tuple[np.ndarray, list[int]]:
    """Partition 0..255 into classes equivalent across every NFA edge set.
    Returns (byte2class [256], representative byte per class)."""
    unique_sets: list[frozenset] = []
    seen: dict[frozenset, int] = {}
    for node_edges in nfa.edges:
        for chars, _ in node_edges:
            if chars not in seen:
                seen[chars] = len(unique_sets)
                unique_sets.append(chars)
    sig = np.zeros((256, len(unique_sets)), np.bool_)
    for j, chars in enumerate(unique_sets):
        for b in chars:
            sig[b, j] = True
    _, byte2class = np.unique(sig, axis=0, return_inverse=True)
    reps: dict[int, int] = {}
    for b in range(256):
        reps.setdefault(int(byte2class[b]), b)
    rep_list = [reps[c] for c in range(len(reps))]
    return byte2class.astype(np.int32), rep_list


def _eps_closure(nfa: _NFA, states: frozenset) -> frozenset:
    stack = list(states)
    out = set(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


def _subset_construct(nfa: _NFA, start: int, accept: int) -> CharDFA:
    byte2class, reps = _byte_classes(nfa)
    K = len(reps)
    start_set = _eps_closure(nfa, frozenset((start,)))
    ids: dict[frozenset, int] = {start_set: 0}
    rows: list[list[int]] = []
    accepts: list[bool] = [accept in start_set]
    work = [start_set]
    while work:
        cur = work.pop()
        i = ids[cur]
        while len(rows) <= i:
            rows.append([-1] * K)
        for c in range(K):
            rb = reps[c]
            targets = set()
            for s in cur:
                for chars, t in nfa.edges[s]:
                    if rb in chars:
                        targets.add(t)
            if not targets:
                continue
            nxt = _eps_closure(nfa, frozenset(targets))
            if nxt not in ids:
                if len(ids) >= MAX_DFA_STATES:
                    raise GrammarError(
                        f"grammar too large (> {MAX_DFA_STATES} DFA states)")
                ids[nxt] = len(ids)
                accepts.append(accept in nxt)
                work.append(nxt)
            rows[i][c] = ids[nxt]
    table = np.asarray(rows, np.int32)
    return _minimize(CharDFA(table, np.asarray(accepts, np.bool_),
                             byte2class, 0))


def _minimize(dfa: CharDFA) -> CharDFA:
    """Moore partition refinement (numpy row-signature rounds)."""
    S, K = dfa.table.shape
    # append an explicit dead state so -1 participates in refinement
    table = np.vstack([dfa.table, np.full((1, K), S, np.int32)])
    table = np.where(table < 0, S, table)
    accept = np.concatenate([dfa.accept, [False]])
    labels = accept.astype(np.int64)
    while True:
        sig = np.concatenate([labels[:, None], labels[table]], axis=1)
        _, new = np.unique(sig, axis=0, return_inverse=True)
        if np.array_equal(new, labels):
            break
        labels = new
    # rebuild: one representative per label; start's label first
    n_lab = int(labels.max()) + 1
    rep_state = np.full(n_lab, -1, np.int64)
    for s in range(S + 1):
        if rep_state[labels[s]] < 0:
            rep_state[labels[s]] = s
    dead_lab = labels[S]
    # map labels -> compact ids with start first, dead dropped
    order = [int(labels[dfa.start])] + [
        int(l) for l in range(n_lab)
        if l != labels[dfa.start] and l != dead_lab]
    lab2new = {l: i for i, l in enumerate(order)}
    S2 = len(order)
    out = np.full((S2, K), -1, np.int32)
    acc2 = np.zeros(S2, np.bool_)
    for l, i in lab2new.items():
        r = int(rep_state[l])
        acc2[i] = accept[r]
        for c in range(K):
            t_lab = int(labels[table[r, c]])
            if t_lab != dead_lab:
                out[i, c] = lab2new[t_lab]
    return CharDFA(out, acc2, dfa.byte2class, 0)


def compile_char_dfa(ast) -> CharDFA:
    nfa = _NFA()
    s, e = nfa.build(ast)
    return _subset_construct(nfa, s, e)


# ---------------------------------------------------------------------------
# JSON grammar ASTs
# ---------------------------------------------------------------------------

_ws = star(cls(_WS))


def _utf8_char_ast():
    """One well-formed UTF-8 character >= 0x20, excluding '"' and '\\'."""
    cont = cls(_CONT)
    return alt(
        cls(_ASCII_CHAR),
        seq(cls(frozenset(range(0xC2, 0xE0))), cont),
        seq(lit(b"\xe0"), cls(frozenset(range(0xA0, 0xC0))), cont),
        seq(cls(frozenset(range(0xE1, 0xED))), cont, cont),
        seq(lit(b"\xed"), cls(frozenset(range(0x80, 0xA0))), cont),
        seq(cls(frozenset((0xEE, 0xEF))), cont, cont),
        seq(lit(b"\xf0"), cls(frozenset(range(0x90, 0xC0))), cont, cont),
        seq(cls(frozenset(range(0xF1, 0xF4))), cont, cont, cont),
        seq(lit(b"\xf4"), cls(frozenset(range(0x80, 0x90))), cont, cont),
    )


def _json_string_ast(min_len: Optional[int] = None,
                     max_len: Optional[int] = None):
    escape = seq(lit("\\"), alt(cls(frozenset(b'"\\/bfnrt')),
                                seq(lit("u"), rep(cls(_HEX), 4, 4))))
    ch = alt(_utf8_char_ast(), escape)
    if min_len is None and max_len is None:
        body = star(ch)
    else:
        lo = int(min_len or 0)
        hi = None if max_len is None else int(max_len)
        body = rep(ch, lo, hi)
    return seq(lit('"'), body, lit('"'))


def _json_number_ast(integer: bool = False):
    int_part = seq(opt(lit("-")),
                   alt(lit("0"), seq(cls(_DIGITS19), star(cls(_DIGITS)))))
    if integer:
        return int_part
    frac = opt(seq(lit("."), cls(_DIGITS), star(cls(_DIGITS))))
    expo = opt(seq(cls(frozenset(b"eE")), opt(cls(frozenset(b"+-"))),
                   cls(_DIGITS), star(cls(_DIGITS))))
    return seq(int_part, frac, expo)


def _json_value_ast(depth: int):
    """Generic JSON value, object/array nesting unrolled to ``depth``."""
    scalars = [_json_string_ast(), _json_number_ast(),
               lit("true"), lit("false"), lit("null")]
    if depth <= 0:
        return alt(*scalars)
    inner = _json_value_ast(depth - 1)
    return alt(*scalars, _json_object_ast(inner), _json_array_ast(inner))


def _json_object_ast(value_ast):
    member = seq(_json_string_ast(), _ws, lit(":"), _ws, value_ast)
    members = seplist(member, seq(_ws, lit(","), _ws))
    return seq(lit("{"), _ws, opt(seq(members, _ws)), lit("}"))


def _json_array_ast(value_ast):
    items = seplist(value_ast, seq(_ws, lit(","), _ws))
    return seq(lit("["), _ws, opt(seq(items, _ws)), lit("]"))


# ---------------------------------------------------------------------------
# JSON-schema -> AST
# ---------------------------------------------------------------------------

_UNSUPPORTED = ("$ref", "allOf", "not", "if", "then", "else",
                "patternProperties", "pattern", "contains",
                "dependentSchemas", "dependentRequired", "propertyNames",
                "unevaluatedProperties", "unevaluatedItems")


def _schema_ast(schema: Any, depth: int):
    if schema is True or schema == {}:
        return _json_value_ast(depth)
    if schema is False:
        raise GrammarError("schema 'false' matches nothing")
    if not isinstance(schema, dict):
        raise GrammarError(f"schema must be an object, got {type(schema).__name__}")
    for key in _UNSUPPORTED:
        if key in schema:
            raise GrammarError(f"unsupported JSON-schema construct {key!r}")

    if "const" in schema:
        return lit(json.dumps(schema["const"], separators=(",", ":"),
                              ensure_ascii=False))
    if "enum" in schema:
        if not isinstance(schema["enum"], list) or not schema["enum"]:
            raise GrammarError("enum must be a non-empty list")
        return alt(*[lit(json.dumps(v, separators=(",", ":"),
                                    ensure_ascii=False))
                     for v in schema["enum"]])
    if "anyOf" in schema or "oneOf" in schema:
        variants = schema.get("anyOf", schema.get("oneOf"))
        if not isinstance(variants, list) or not variants:
            raise GrammarError("anyOf/oneOf must be a non-empty list")
        # oneOf exclusivity is not FSM-expressible; treated as anyOf
        return alt(*[_schema_ast(v, depth) for v in variants])

    t = schema.get("type")
    if isinstance(t, list):
        if not t:
            raise GrammarError("type list must be non-empty")
        return alt(*[_schema_ast({**schema, "type": one}, depth) for one in t])
    if t is None:
        if "properties" in schema or "required" in schema:
            t = "object"
        elif "items" in schema or "prefixItems" in schema:
            t = "array"
        else:
            return _json_value_ast(depth)

    if t == "string":
        mn, mx = schema.get("minLength"), schema.get("maxLength")
        for v in (mn, mx):
            if v is not None and (not isinstance(v, int) or v < 0):
                raise GrammarError("minLength/maxLength must be ints >= 0")
        if mx is not None and mx > MAX_REP:
            raise GrammarError(f"maxLength {mx} exceeds {MAX_REP}")
        return _json_string_ast(mn, mx)
    if t == "number":
        return _json_number_ast()
    if t == "integer":
        return _json_number_ast(integer=True)
    if t == "boolean":
        return alt(lit("true"), lit("false"))
    if t == "null":
        return lit("null")
    if t == "array":
        return _schema_array_ast(schema, depth)
    if t == "object":
        return _schema_object_ast(schema, depth)
    raise GrammarError(f"unsupported schema type {t!r}")


def _schema_array_ast(schema: dict, depth: int):
    prefix = schema.get("prefixItems")
    items = schema.get("items")
    mn, mx = schema.get("minItems", 0), schema.get("maxItems")
    for v in (mn, mx):
        if v is not None and (not isinstance(v, int) or v < 0):
            raise GrammarError("minItems/maxItems must be ints >= 0")
    sep = seq(_ws, lit(","), _ws)
    if prefix is not None:
        if not isinstance(prefix, list) or not prefix:
            raise GrammarError("prefixItems must be a non-empty list")
        k = len(prefix)
        if mx is not None and mx < k:
            raise GrammarError(
                f"maxItems {mx} below the {k} prefixItems (the generator "
                f"always emits the full prefix)")
        if mn > k and items in (None, False):
            raise GrammarError(
                f"minItems {mn} exceeds the {k} prefixItems with no "
                f"items schema for the rest")
        parts = [_schema_ast(p, depth - 1) for p in prefix]
        body = parts[0]
        for p in parts[1:]:
            body = seq(body, sep, p)
        if items not in (None, False):
            extra = _schema_ast(items if items is not True else {}, depth - 1)
            body = seq(body, rep(seq(sep, extra), max(0, mn - k),
                                 None if mx is None else mx - k))
        return seq(lit("["), _ws, body, _ws, lit("]"))
    item = _schema_ast(items if items is not None else {}, depth - 1)
    if mx is not None and mx > MAX_REP:
        raise GrammarError(f"maxItems {mx} exceeds {MAX_REP}")
    if mn == 0 and mx is None:
        body = opt(seq(seplist(item, sep), _ws))
        return seq(lit("["), _ws, body, lit("]"))
    # bounded: item (sep item){mn-1..mx-1}
    if mx == 0:
        return seq(lit("["), _ws, lit("]"))
    body = seq(item, rep(seq(sep, item), max(0, mn - 1),
                         None if mx is None else mx - 1))
    inner = opt(seq(body, _ws)) if mn == 0 else seq(body, _ws)
    return seq(lit("["), _ws, inner, lit("]"))


def _schema_object_ast(schema: dict, depth: int):
    props = schema.get("properties")
    required = schema.get("required", [])
    if not isinstance(required, list):
        raise GrammarError("required must be a list")
    if props is None:
        if required:
            raise GrammarError(
                "required without properties is not supported")
        inner = _json_value_ast(max(0, depth - 1))
        return _json_object_ast(inner)
    if not isinstance(props, dict):
        raise GrammarError("properties must be an object")
    unknown = [r for r in required if r not in props]
    if unknown:
        raise GrammarError(f"required names not in properties: {unknown}")
    if not props:
        return seq(lit("{"), _ws, lit("}"))
    # Emit properties in DECLARED ORDER (outlines/vLLM convention);
    # optional ones are skippable. The "members" NFA node keeps this
    # linear in the property count (see _NFA.build).
    members = []
    for name, sub in props.items():
        key = lit(json.dumps(name, ensure_ascii=False))
        members.append((name in set(required),
                        seq(key, _ws, lit(":"), _ws,
                            _schema_ast(sub, depth - 1))))
    comma = seq(_ws, lit(","), _ws)
    return seq(lit("{"), _ws, ("members", members, comma), _ws, lit("}"))


# ---------------------------------------------------------------------------
# top-level grammar specs
# ---------------------------------------------------------------------------

_trail_ws = rep(cls(_WS), 0, 2)


def json_object_ast(depth: int = DEFAULT_DEPTH):
    """OpenAI ``response_format: {"type": "json_object"}``: any JSON
    object (nesting bounded at ``depth``)."""
    inner = _json_value_ast(depth - 1)
    return seq(_ws, _json_object_ast(inner), _trail_ws)


def json_schema_ast(schema: Any, depth: int = DEFAULT_DEPTH):
    return seq(_ws, _schema_ast(schema, depth), _trail_ws)


def tool_call_ast(tools: Sequence[dict], force_name: Optional[str],
                  depth: int = DEFAULT_DEPTH):
    """Forced tool calling: ``<tool_call>{"name": ..., "arguments": {...}}
    </tool_call>`` blocks (the Hermes/Qwen convention ToolStreamParser
    extracts — server/tools.py). ``force_name`` pins a single call to one
    function; None ("required") allows 1+ calls to any listed tool."""
    variants = []
    for t in tools:
        fn = t["function"]
        name = fn["name"]
        if force_name is not None and name != force_name:
            continue
        params = fn.get("parameters")
        if params in (None, {}):
            args_ast = _schema_object_ast({"properties": {}}, depth)
        else:
            args_ast = _schema_ast(params, depth)
        body = seq(lit("{"), _ws,
                   lit(json.dumps("name")), _ws, lit(":"), _ws,
                   lit(json.dumps(name, ensure_ascii=False)),
                   _ws, lit(","), _ws,
                   lit(json.dumps("arguments")), _ws, lit(":"), _ws,
                   args_ast, _ws, lit("}"))
        variants.append(seq(lit("<tool_call>"), _ws, body, _ws,
                            lit("</tool_call>")))
    if not variants:
        raise GrammarError(f"tool_choice names unknown function {force_name!r}")
    call = alt(*variants)
    if force_name is not None:
        return seq(_ws, call, _trail_ws)
    return seq(_ws, seplist(call, _ws), _trail_ws)


# ---------------------------------------------------------------------------
# tokenizer composition: char DFA -> token-level tables
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CompiledGrammar:
    """Token-level DFA ready for the engine's device tables.

    ``trans[s, class_of[tok]]`` is the next state after emitting ``tok``
    from state ``s`` (-1 = not allowed). ``start`` is the initial state;
    EOS tokens transition accepting states into an absorbing DONE state
    (every token allowed — post-EOS steps are speculative garbage the
    engine discards). ``key`` identifies the grammar for dedup/caching."""

    key: str
    class_of: np.ndarray     # [V] int16
    trans: np.ndarray        # [S, C] int16
    start: int
    n_states: int            # S
    n_classes: int           # C

    def next_state(self, state: int, token: int) -> int:
        return int(self.trans[state, self.class_of[token]])

    def allowed(self, state: int) -> np.ndarray:
        """Host-side debug/test helper: bool[V] of allowed tokens."""
        return self.trans[state][self.class_of] >= 0


def compile_token_dfa(char_dfa: CharDFA,
                      token_bytes: "list[Optional[bytes]]",
                      eos_ids: Sequence[int],
                      key: str = "",
                      max_states: int = MAX_DFA_STATES) -> CompiledGrammar:
    """Compose a char DFA with a tokenizer's vocabulary.

    The naive build is O(states x vocab x token-length) — minutes at a
    128K BPE vocab. Two collapses make it sub-second:

    1. A token's effect on the DFA depends only on its BYTE-CLASS
       sequence; a 128K vocab has far fewer unique class sequences
       (plain-word tokens all look alike to a JSON grammar).
    2. Unique sequences sorted lexicographically share prefixes; a
       stack-based traversal composes each transition vector [S] once
       per distinct trie node, not once per (state, token).

    The resulting end-state vectors (one per unique sequence) ARE the
    transition table's columns; np.unique over them yields the token
    equivalence classes directly — the [S, V] table is never built."""
    V = len(token_bytes)
    eos = [e for e in set(int(e) for e in eos_ids) if 0 <= e < V]
    if not eos:
        raise GrammarError("no EOS token id inside the vocabulary")
    table = char_dfa.table                      # [S, K] int32
    S = int(table.shape[0])
    if S + 1 > max_states:
        raise GrammarError(
            f"grammar too large (> {max_states} token-DFA states)")

    lens = np.asarray([-1 if t is None else len(t) for t in token_bytes],
                      np.int32)
    Lmax = max(1, int(lens.max(initial=0)))
    mat = np.full((V, Lmax), 255, np.uint8)     # 255 = past-end pad
    b2c = char_dfa.byte2class.astype(np.uint8)
    for i, t in enumerate(token_bytes):
        if t:
            arr = np.frombuffer(t, np.uint8)
            mat[i, :len(arr)] = b2c[arr]

    # unique class sequences (padded rows are unique keys: 255 never a class)
    seqs, inv = np.unique(mat, axis=0, return_inverse=True)
    inv = inv.reshape(-1)
    U = seqs.shape[0]

    # Level-synchronous trie walk with STATE-VECTOR interning: every trie
    # node's effect is a function [S]->[S or dead]; distinct functions are
    # few (most prefixes collapse to "rejected everywhere except
    # string-interior"), so each level is one batched gather over the
    # distinct (function, class) pairs. -1 (reject) rides as dead index S.
    tbl = np.vstack([table, np.full((1, table.shape[1]), -1, np.int32)])
    tbl = np.where(tbl < 0, S, tbl)             # dead state index S
    ident = np.arange(S, dtype=np.int32)
    vec_rows = [ident]                           # id -> [S] vector
    vec_ids: dict[bytes, int] = {ident.tobytes(): 0}
    seq_vec = np.zeros(U, np.int64)              # per-sequence current id
    K = int(tbl.shape[1])
    for d in range(Lmax):
        act = np.nonzero(seqs[:, d] != 255)[0]
        if act.size == 0:
            break
        pairs = seq_vec[act] * 256 + seqs[act, d]
        upairs, pinv = np.unique(pairs, return_inverse=True)
        parents = (upairs // 256).astype(np.int64)
        classes = (upairs % 256).astype(np.int64)
        stacked = np.asarray([vec_rows[p] for p in parents])   # [P, S]
        children = tbl[stacked, classes[:, None]]              # [P, S]
        child_ids = np.empty(len(upairs), np.int64)
        for j in range(children.shape[0]):
            key = children[j].tobytes()
            cid = vec_ids.get(key)
            if cid is None:
                cid = len(vec_rows)
                vec_ids[key] = cid
                vec_rows.append(children[j])
            child_ids[j] = cid
        seq_vec[act] = child_ids[pinv]

    # token classes = distinct terminal vectors (already interned) +
    # dedicated reject/EOS columns
    terml, tinv = np.unique(seq_vec, return_inverse=True)
    C = len(terml)
    uF = np.asarray([vec_rows[t] for t in terml])  # [C, S]
    uF[uF == S] = -1
    class_of = tinv[inv].astype(np.int32)
    class_of[lens <= 0] = C            # specials/empty: reject class
    c_eos = C + 1
    class_of[eos] = c_eos              # EOS class (overrides byte content)

    DONE = S
    trans = np.full((S + 1, C + 2), -1, np.int32)
    trans[:S, :C] = uF.T
    trans[:S, C] = -1
    trans[:S, c_eos] = np.where(char_dfa.accept[:S], DONE, -1)
    trans[DONE, :] = DONE  # post-EOS steps are speculative; allow anything

    # dead-end check: every state reachable at a token boundary must allow
    # some token (byte-fallback vocabs make this true by construction; a
    # vocab missing bytes could violate it, which would let sampling emit
    # garbage silently)
    reachable = np.zeros(S + 1, np.bool_)
    frontier = [char_dfa.start]
    reachable[char_dfa.start] = True
    while frontier:
        s = frontier.pop()
        for t in np.unique(trans[s]):
            if t >= 0 and not reachable[t]:
                reachable[t] = True
                frontier.append(int(t))
    dead = np.nonzero(reachable & ~(trans >= 0).any(axis=1))[0]
    if dead.size:
        raise GrammarError(
            f"grammar has {dead.size} token-level dead-end state(s) — the "
            f"vocabulary cannot continue the pattern from there")

    if S + 1 > 32767 or C + 2 > 32767:
        raise GrammarError("grammar exceeds int16 table range")
    return CompiledGrammar(
        key=key,
        class_of=class_of.astype(np.int16),
        trans=trans.astype(np.int16),
        start=int(char_dfa.start),
        n_states=S + 1,
        n_classes=C + 2,
    )


# ---------------------------------------------------------------------------
# tokenizer vocab -> byte strings
# ---------------------------------------------------------------------------


def _gpt2_byte_decoder() -> dict[str, int]:
    """The byte-level-BPE unicode<->byte table (GPT-2 convention, used by
    Llama-3 / Qwen / GPT-NeoX tokenizers)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_bytes_of(tokenizer) -> "list[Optional[bytes]]":
    """Token id -> raw byte string (None = special/control token, never
    allowed inside a grammar). Handles the engine's tokenizer families:
    ByteTokenizer (tests), HF byte-level BPE, HF/GGUF SentencePiece."""
    # ByteTokenizer: ids 0..255 are the bytes themselves
    from llms_on_kubernetes_tpu.engine.tokenizer import ByteTokenizer

    if isinstance(tokenizer, ByteTokenizer):
        out: "list[Optional[bytes]]" = [bytes([i]) for i in range(256)]
        out += [None, None]  # BOS, EOS
        return out

    # GGUF (SentencePiece vocab embedded in the model file)
    tokens = getattr(tokenizer, "tokens", None)
    if tokens is not None and isinstance(tokens, list):
        control = getattr(tokenizer, "_control", set())
        byte_ids = set(getattr(tokenizer, "_byte_ids", {}).values())
        out = []
        for i, t in enumerate(tokens):
            if i in control:
                out.append(None)
            elif i in byte_ids or (t.startswith("<0x") and t.endswith(">")):
                try:
                    out.append(bytes([int(t[3:-1], 16)]))
                except ValueError:
                    out.append(None)
            else:
                out.append(t.replace("▁", " ").encode("utf-8"))
        return out

    # HF AutoTokenizer wrapper
    tok = getattr(tokenizer, "_tok", tokenizer)
    if not hasattr(tok, "convert_ids_to_tokens"):
        raise GrammarError(
            f"cannot derive a token byte map from {type(tokenizer).__name__}")
    vocab_size = len(tok)
    specials = set(getattr(tok, "all_special_ids", []) or [])
    added = getattr(tok, "added_tokens_decoder", {}) or {}
    specials |= {int(i) for i in added.keys()}
    pieces = tok.convert_ids_to_tokens(list(range(vocab_size)))
    spm = any("▁" in (p or "") for p in pieces[:4000])
    byte_dec = None if spm else _gpt2_byte_decoder()
    out = []
    for i, p in enumerate(pieces):
        if i in specials or p is None:
            out.append(None)
            continue
        if spm:
            if p.startswith("<0x") and p.endswith(">") and len(p) == 6:
                try:
                    out.append(bytes([int(p[3:-1], 16)]))
                    continue
                except ValueError:
                    pass
            out.append(p.replace("▁", " ").encode("utf-8"))
        else:
            try:
                out.append(bytes(byte_dec[ch] for ch in p))
            except KeyError:
                out.append(None)  # non-byte-level added piece
    return out


def vocab_fingerprint(token_bytes: "list[Optional[bytes]]") -> str:
    h = hashlib.sha256()
    for t in token_bytes:
        h.update(b"\xff\x00" if t is None else t + b"\xff\x01")
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# compile entry points (with a small in-process cache)
# ---------------------------------------------------------------------------

_cache: "dict[tuple, CompiledGrammar]" = {}
_CACHE_MAX = 32


def _cached(key: tuple, build) -> CompiledGrammar:
    hit = _cache.get(key)
    if hit is not None:
        return hit
    g = build()
    if len(_cache) >= _CACHE_MAX:
        _cache.pop(next(iter(_cache)))
    _cache[key] = g
    return g


def compile_response_format(response_format: dict,
                            token_bytes: "list[Optional[bytes]]",
                            eos_ids: Sequence[int],
                            depth: int = DEFAULT_DEPTH) -> Optional[CompiledGrammar]:
    """OpenAI ``response_format`` -> CompiledGrammar (None for type=text).
    Raises GrammarError (HTTP 400) on unsupported shapes/constructs."""
    if not isinstance(response_format, dict):
        raise GrammarError("response_format must be an object")
    kind = response_format.get("type")
    if kind in (None, "text"):
        return None
    fp = vocab_fingerprint(token_bytes)
    eos_key = tuple(sorted(set(int(e) for e in eos_ids)))
    if kind == "json_object":
        key = ("json_object", depth, fp, eos_key)
        return _cached(key, lambda: compile_token_dfa(
            compile_char_dfa(json_object_ast(depth)), token_bytes, eos_ids,
            key="json_object"))
    if kind == "json_schema":
        js = response_format.get("json_schema")
        if not isinstance(js, dict) or "schema" not in js:
            raise GrammarError(
                "response_format json_schema needs {'json_schema': "
                "{'schema': {...}}}")
        schema = js["schema"]
        skey = json.dumps(schema, sort_keys=True, separators=(",", ":"))
        key = ("json_schema", skey, depth, fp, eos_key)
        return _cached(key, lambda: compile_token_dfa(
            compile_char_dfa(json_schema_ast(schema, depth)), token_bytes,
            eos_ids, key="schema:" + hashlib.sha256(
                skey.encode()).hexdigest()[:16]))
    raise GrammarError(
        f"response_format type {kind!r} is not supported "
        f"(text | json_object | json_schema)")


def compile_tool_choice(tools: Sequence[dict], force_name: Optional[str],
                        token_bytes: "list[Optional[bytes]]",
                        eos_ids: Sequence[int],
                        depth: int = DEFAULT_DEPTH) -> CompiledGrammar:
    """Grammar for ``tool_choice: required`` (force_name=None) or a named
    function — the sampled stream CANNOT be plain text."""
    tools_key = json.dumps(tools, sort_keys=True, separators=(",", ":"))
    fp = vocab_fingerprint(token_bytes)
    eos_key = tuple(sorted(set(int(e) for e in eos_ids)))
    key = ("tools", tools_key, force_name, depth, fp, eos_key)
    return _cached(key, lambda: compile_token_dfa(
        compile_char_dfa(tool_call_ast(tools, force_name, depth)),
        token_bytes, eos_ids,
        key="tools:" + hashlib.sha256(
            (tools_key + "|" + str(force_name)).encode()).hexdigest()[:16]))

"""The serving engine: continuous batching over jitted prefill/decode steps.

This is the TPU-native replacement for the vLLM container the reference
pulled (reference vllm-models/helm-chart/templates/model-deployments.yaml:21
— continuous batching, paged attention, OpenAI serving all lived in that
image). Design, per SURVEY §7 "hard parts" #2:

- **Static shapes under jit.** Decode runs a fixed slot batch
  [max_decode_slots]; idle slots ride along with length 0. Prompts are
  padded to a small set of prefill buckets. Result: exactly
  1 + len(buckets) compiled executables, no recompilation storms.
- **One scheduler iteration** = admit-waiting → prefill (≤1 bucket call) →
  one decode step for all active slots. Tokens stream out per iteration —
  requests join/leave the batch without stopping it (continuous batching).
- **Paged KV** (engine/cache.py): pages allocated on demand per step;
  pool exhaustion preempts the youngest request back to the wait queue
  (it re-prefills later — prompt + generated so far).
- **Sampling fused into the step** (engine/sampling.py): only [B] int32
  token ids cross the host boundary per step.

The engine is synchronous; server/openai_api.py runs it on a thread and
bridges to asyncio.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from llms_on_kubernetes_tpu.configs import ModelConfig, get_config
from llms_on_kubernetes_tpu.engine.cache import CacheConfig, PageAllocator, init_pages
from llms_on_kubernetes_tpu.engine.sampling import sample
from llms_on_kubernetes_tpu.models.decoder import forward_decode, forward_prefill, init_params

Params = dict[str, Any]


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 1.0
    top_k: int = 0          # 0 => disabled
    top_p: float = 1.0
    max_tokens: int = 128
    stop_token_ids: tuple[int, ...] = ()
    seed: Optional[int] = None


@dataclasses.dataclass
class EngineConfig:
    model: str = "debug-tiny"
    dtype: str = "bfloat16"
    max_decode_slots: int = 8
    page_size: int = 64
    num_pages: int = 512
    pages_per_slot: int = 32
    prefill_buckets: tuple[int, ...] = (64, 256, 1024)
    quantization: Optional[str] = None  # None | "int8" (weight-only)
    # multi-host pod group: coordinator broadcasts each step's inputs so
    # follower processes enter the same SPMD programs (engine/multihost.py)
    multihost: bool = False
    seed: int = 0

    @property
    def max_model_len(self) -> int:
        return self.page_size * self.pages_per_slot


@dataclasses.dataclass
class Request:
    id: str
    prompt: list[int]
    params: SamplingParams
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    # runtime state
    output: list[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    pending_token: int = -1        # sampled but KV not yet cached
    finished: bool = False
    finish_reason: Optional[str] = None
    abort_reason: Optional[str] = None  # set by any thread; reaped by step()
    first_token_at: Optional[float] = None
    events: "queue.SimpleQueue[tuple[list[int], bool, Optional[str]]]" = dataclasses.field(
        default_factory=queue.SimpleQueue
    )


@dataclasses.dataclass
class StepEvent:
    request: Request
    new_tokens: list[int]
    finished: bool
    finish_reason: Optional[str]


def _prefill_step(params, cfg, tokens, lengths, k_pages, v_pages, page_table,
                  key, temps, top_ks, top_ps):
    logits, k_pages, v_pages = forward_prefill(
        params, cfg, tokens, lengths, k_pages, v_pages, page_table
    )
    toks, logprobs = sample(logits, key, temps, top_ks, top_ps)
    return toks, logprobs, k_pages, v_pages


def _decode_step(params, cfg, tokens, lengths, k_pages, v_pages, page_table,
                 key, temps, top_ks, top_ps):
    logits, k_pages, v_pages = forward_decode(
        params, cfg, tokens, lengths, k_pages, v_pages, page_table
    )
    toks, logprobs = sample(logits, key, temps, top_ks, top_ps)
    return toks, logprobs, k_pages, v_pages


class Engine:
    """Multi-request continuous-batching engine for one model."""

    def __init__(
        self,
        engine_config: EngineConfig,
        model_config: Optional[ModelConfig] = None,
        params: Optional[Params] = None,
        mesh=None,
        model_dir: Optional[str] = None,
    ):
        from llms_on_kubernetes_tpu.ops.quant import SUPPORTED_QUANTIZATIONS

        self.config = engine_config
        if engine_config.quantization not in SUPPORTED_QUANTIZATIONS:
            raise ValueError(
                f"unknown quantization {engine_config.quantization!r} "
                f"(supported: {[q for q in SUPPORTED_QUANTIZATIONS if q]})"
            )
        self.model_config = model_config or get_config(engine_config.model)
        cfg = self.model_config
        self.mesh = mesh

        if params is not None:
            self.params = params
        elif model_dir is not None:
            from llms_on_kubernetes_tpu.engine.weights import load_hf_params
            self.params = load_hf_params(
                cfg, model_dir, mesh=mesh, dtype=engine_config.dtype,
                quantization=engine_config.quantization,
            )
        else:  # random weights (tests / benchmarks)
            self.params = init_params(cfg, jax.random.key(engine_config.seed),
                                      dtype=engine_config.dtype)
            if engine_config.quantization == "int8":
                from llms_on_kubernetes_tpu.ops.quant import quantize_params
                self.params = quantize_params(self.params)
            if mesh is not None:
                from llms_on_kubernetes_tpu.parallel.sharding import shard_params
                self.params = shard_params(self.params, cfg, mesh)

        self.cache_config = CacheConfig(
            num_layers=cfg.num_layers,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            num_pages=engine_config.num_pages,
            page_size=engine_config.page_size,
            pages_per_slot=engine_config.pages_per_slot,
            dtype=engine_config.dtype,
        )
        self.k_pages, self.v_pages = init_pages(self.cache_config)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from llms_on_kubernetes_tpu.parallel.sharding import cache_specs
            ks, vs = cache_specs(cfg, mesh)
            self.k_pages = jax.device_put(self.k_pages, NamedSharding(mesh, ks))
            self.v_pages = jax.device_put(self.v_pages, NamedSharding(mesh, vs))

        B = engine_config.max_decode_slots
        self.allocator = PageAllocator(
            engine_config.num_pages, engine_config.page_size, B,
            engine_config.pages_per_slot,
        )
        self.slots: list[Optional[Request]] = [None] * B
        self.slot_len = np.zeros((B,), np.int64)  # tokens whose KV is cached
        self.waiting: "collections.deque[Request]" = collections.deque()
        self._key = jax.random.key(engine_config.seed)
        self._step_counter = itertools.count()
        self._id_counter = itertools.count()
        self._lock = threading.Lock()
        self.preemptions = 0  # total KV-pressure preemptions (metrics)

        self._prefill = jax.jit(
            _prefill_step, static_argnums=(1,), donate_argnums=(4, 5)
        )
        self._decode = jax.jit(
            _decode_step, static_argnums=(1,), donate_argnums=(4, 5)
        )

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def submit(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
    ) -> Request:
        params = params or SamplingParams()
        max_len = self.config.max_model_len
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) > max(self.config.prefill_buckets):
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the largest prefill "
                f"bucket ({max(self.config.prefill_buckets)})"
            )
        # prompt + 1 sampled token must fit a slot's pages — a prompt that can
        # never be admitted would livelock the whole waiting queue behind it.
        if len(prompt) + 1 > max_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens cannot fit max_model_len="
                f"{max_len} (page_size*pages_per_slot) with room to generate"
            )
        if len(prompt) + params.max_tokens > max_len:
            params = dataclasses.replace(
                params, max_tokens=max(1, max_len - len(prompt))
            )
        req = Request(
            id=request_id or f"req-{next(self._id_counter)}",
            prompt=list(prompt), params=params,
        )
        with self._lock:
            self.waiting.append(req)
        return req

    def has_work(self) -> bool:
        return bool(self.waiting) or any(r is not None for r in self.slots)

    # ------------------------------------------------------------------
    # scheduler iteration
    # ------------------------------------------------------------------

    def step(self) -> list[StepEvent]:
        events: list[StepEvent] = []
        events += self._reap_aborted()
        events += self._admit_one()
        events += self._decode_once()
        for ev in events:
            ev.request.events.put((ev.new_tokens, ev.finished, ev.finish_reason))
        return events

    def abort(self, req: Request, reason: str = "abort") -> None:
        """Request cancellation from any thread (client disconnect, server-side
        stop sequence). The engine thread releases the slot/pages at the start
        of its next step and emits a final finished event."""
        req.abort_reason = reason

    def _reap_aborted(self) -> list[StepEvent]:
        events: list[StepEvent] = []
        with self._lock:
            doomed_waiting = [r for r in self.waiting
                              if r.abort_reason and not r.finished]
            for r in doomed_waiting:
                self.waiting.remove(r)
        for r in doomed_waiting:
            events.append(self._finish(r, r.abort_reason))
        for r in list(self.slots):
            if r is not None and r.abort_reason and not r.finished:
                events.append(self._finish(r, r.abort_reason))
        return events

    def _next_key(self) -> jax.Array:
        return jax.random.fold_in(self._key, next(self._step_counter))

    def _run_device_step(self, op: int, fn, tokens: np.ndarray,
                         lengths: np.ndarray, page_table: np.ndarray,
                         temps: np.ndarray, top_ks: np.ndarray,
                         top_ps: np.ndarray):
        """Enter a jitted step — after broadcasting its inputs to follower
        processes when this engine coordinates a multi-host pod group."""
        step = next(self._step_counter)
        key = jax.random.fold_in(self._key, step)
        if self.config.multihost:
            from llms_on_kubernetes_tpu.engine import multihost as mh

            bucket = tokens.shape[1] if tokens.ndim == 2 else 0
            mh.broadcast_header(op, bucket, tokens.shape[0])
            mh.broadcast_payload(
                {"tokens": np.asarray(tokens, np.int32),
                 "lengths": np.asarray(lengths, np.int32),
                 "page_table": np.asarray(page_table, np.int32),
                 "temps": np.asarray(temps, np.float32),
                 "top_ks": np.asarray(top_ks, np.int32),
                 "top_ps": np.asarray(top_ps, np.float32),
                 "step": np.asarray(step, np.int64)},
                op, bucket, tokens.shape[0], self.config.pages_per_slot,
            )
        return fn(
            self.params, self.model_config, jnp.asarray(tokens),
            jnp.asarray(lengths), self.k_pages, self.v_pages,
            jnp.asarray(page_table), key, jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps),
        )

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"no prefill bucket fits {n} tokens")

    def _admit_one(self) -> list[StepEvent]:
        """Admit + prefill at most one waiting request per iteration.

        A resumed (previously preempted) request re-prefills its prompt plus
        every already-emitted token except the pending one; the prefill's
        sampled token is discarded and the old pending token is restored, so
        the output stream is unaffected by preemption.
        """
        with self._lock:
            if not self.waiting:
                return []
            slot = self._free_slot()
            if slot is None:
                return []
            req = self.waiting[0]
            resumed = bool(req.output)
            prefill_tokens = req.prompt + (req.output[:-1] if resumed else [])
            n = len(prefill_tokens)
            if (n > max(self.config.prefill_buckets)
                    or self.allocator.pages_needed(n + 1) > self.allocator.pages_per_slot):
                # resumed request grew beyond prefill/page reach; end it
                # gracefully rather than livelocking the queue behind it
                self.waiting.popleft()
                ev = self._finish(req, "length")
                return [ev]
            if not self.allocator.can_allocate(slot, n + 1):
                return []  # wait for pages to free up
            self.waiting.popleft()
        self.allocator.allocate(slot, n + 1)
        self.slots[slot] = req
        req.slot = slot

        bucket = self._bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prefill_tokens

        from llms_on_kubernetes_tpu.engine.multihost import OP_PREFILL

        toks, _lps, self.k_pages, self.v_pages = self._run_device_step(
            OP_PREFILL, self._prefill, tokens,
            np.asarray([n], np.int32),
            self.allocator.page_tables[slot:slot + 1],
            np.asarray([req.params.temperature], np.float32),
            np.asarray([req.params.top_k], np.int32),
            np.asarray([req.params.top_p], np.float32),
        )
        self.slot_len[slot] = n
        if resumed:
            req.pending_token = req.output[-1]
            return []
        first = int(np.asarray(toks)[0])
        req.pending_token = first
        req.first_token_at = time.monotonic()
        return self._emit(req, first)

    def _emit(self, req: Request, token: int) -> list[StepEvent]:
        """Record a sampled token and decide whether the request finishes."""
        req.output.append(token)
        reason = None
        if token in set(req.params.stop_token_ids):
            reason = "stop"
        elif len(req.output) >= req.params.max_tokens:
            reason = "length"
        elif self.slot_len[req.slot] + 1 >= self.config.max_model_len:
            reason = "length"
        if reason is not None:
            self._finish(req, reason)
        return [StepEvent(req, [token], req.finished, reason)]

    def _finish(self, req: Request, reason: str) -> StepEvent:
        """Release a request's slot/pages and mark it finished."""
        req.finished = True
        req.finish_reason = reason
        if req.slot >= 0:
            self.allocator.free(req.slot)
            self.slot_len[req.slot] = 0
            self.slots[req.slot] = None
            req.slot = -1
        return StepEvent(req, [], True, reason)

    def _preempt_youngest(self) -> None:
        """Free the most recently admitted request's pages; requeue it to
        re-prefill (prompt + generated so far) when memory frees up."""
        victims = [r for r in self.slots if r is not None]
        if not victims:
            raise MemoryError("KV pool exhausted with no preemptable request")
        victim = max(victims, key=lambda r: r.submitted_at)
        self.preemptions += 1
        slot = victim.slot
        self.allocator.free(slot)
        self.slot_len[slot] = 0
        self.slots[slot] = None
        victim.slot = -1
        victim.pending_token = -1
        with self._lock:
            self.waiting.appendleft(victim)

    def _decode_once(self) -> list[StepEvent]:
        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return []

        # grow page tables; preempt on exhaustion
        for i, r in list(active):
            while True:
                try:
                    self.allocator.allocate(i, int(self.slot_len[i]) + 1)
                    break
                except MemoryError:
                    self._preempt_youngest()
                    active = [(j, rr) for j, rr in enumerate(self.slots) if rr is not None]
                    if (i, r) not in active:
                        break
        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return []

        B = self.config.max_decode_slots
        tokens = np.zeros((B,), np.int32)
        lengths = np.zeros((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        for i, r in active:
            tokens[i] = r.pending_token
            lengths[i] = self.slot_len[i] + 1
            temps[i] = r.params.temperature
            top_ks[i] = r.params.top_k
            top_ps[i] = r.params.top_p

        from llms_on_kubernetes_tpu.engine.multihost import OP_DECODE

        toks, _lps, self.k_pages, self.v_pages = self._run_device_step(
            OP_DECODE, self._decode, tokens, lengths,
            self.allocator.page_tables, temps, top_ks, top_ps,
        )
        sampled = np.asarray(toks)

        events: list[StepEvent] = []
        for i, r in active:
            self.slot_len[i] += 1  # pending token's KV is now cached
            new = int(sampled[i])
            r.pending_token = new
            events += self._emit(r, new)
        return events

    # ------------------------------------------------------------------
    # convenience
    # ------------------------------------------------------------------

    def generate(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
    ) -> list[int]:
        """Synchronous single-request generation (drives the scheduler)."""
        req = self.submit(prompt, params)
        while not req.finished:
            self.step()
        return req.output

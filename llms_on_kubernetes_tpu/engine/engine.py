"""The serving engine: continuous batching over jitted prefill/decode steps.

This is the TPU-native replacement for the vLLM container the reference
pulled (reference vllm-models/helm-chart/templates/model-deployments.yaml:21
— continuous batching, paged attention, OpenAI serving all lived in that
image). Design, per SURVEY §7 "hard parts" #2:

- **Static shapes under jit.** Decode runs a fixed slot batch
  [max_decode_slots]; idle slots ride along with length 0. Prompts are
  padded to a small set of prefill buckets. Result: exactly
  1 + len(buckets) compiled executables, no recompilation storms.
- **One scheduler iteration** = admit-waiting → prefill (≤1 bucket call) →
  one decode step for all active slots. Tokens stream out per iteration —
  requests join/leave the batch without stopping it (continuous batching).
- **Paged KV** (engine/cache.py): pages allocated on demand per step;
  pool exhaustion preempts the youngest request back to the wait queue
  (it re-prefills later — prompt + generated so far).
- **Sampling fused into the step** (engine/sampling.py): only [B] int32
  token ids cross the host boundary per step.

The engine is synchronous; server/openai_api.py runs it on a thread and
bridges to asyncio.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from llms_on_kubernetes_tpu.configs import ModelConfig, get_config
from llms_on_kubernetes_tpu.engine.cache import (
    CacheConfig, HostKVCache, PageAllocator, init_pages,
)
from llms_on_kubernetes_tpu.engine.qos import (
    TenantFairQueue, normalize_priority, priority_rank,
)
from llms_on_kubernetes_tpu.engine.sampling import (
    MAX_CANDIDATES, HostSample, sample,
)
from llms_on_kubernetes_tpu.models.decoder import (
    forward_chunk, forward_decode, forward_prefill, forward_verify,
    init_params,
)

Params = dict[str, Any]


class EngineStallError(RuntimeError):
    """The device failed to complete a step within the watchdog budget.

    Raised on the engine thread when a wedged device (or its transport)
    stops producing step completions. ``Engine.step`` converts it into a
    clean shed: every in-flight and waiting request finishes with reason
    "stalled", the engine marks itself ``wedged`` (readiness flips, no
    further dispatch), and submit() rejects new work — HTTP 503 upstream
    instead of a hung serving loop."""


class QueueFullError(RuntimeError):
    """Admission rejected: the waiting queue is at max_waiting capacity.
    The API layer maps this to HTTP 429 + Retry-After."""


class UnknownAdapterError(LookupError):
    """The request named a LoRA adapter this engine does not serve. The
    API layer maps this to a structured HTTP 404 (adapter_not_found) —
    NOT the unknown-model fallback."""


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 1.0
    # 0 => no top-k filter; values > sampling.MAX_CANDIDATES are rejected
    # at submit() (the candidate pool is a hard bound — silent clamping
    # would change the semantics the client asked for)
    top_k: int = 0
    top_p: float = 1.0
    max_tokens: int = 128
    stop_token_ids: tuple[int, ...] = ()
    seed: Optional[int] = None
    # OpenAI penalties over the OUTPUT tokens generated so far (vLLM
    # semantics); applied on device from the engine's per-slot counts
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # top alternatives the client asked to see per token (response shaping
    # only — the engine always records LOGPROB_TOPK alternatives)
    logprobs: int = 0
    # OpenAI logit_bias: ((token_id, bias), ...) added to the raw logits
    # on device every step; at most LOGIT_BIAS_SLOTS entries (rejected at
    # submit beyond that — the packed-row column budget is a hard bound)
    logit_bias: tuple = ()
    # grammar-constrained decoding: a grammar.CompiledGrammar (OpenAI
    # response_format json_object/json_schema, forced tool_choice). The
    # engine masks every sampled token to the grammar's allowed set and
    # advances the FSM on device (engine/grammar.py).
    grammar: Optional[Any] = None
    # resume-after-failure: output tokens ALREADY generated for this
    # request by a previous (now dead) replica. The engine seeds
    # req.output with them, so admission takes the resumed re-prefill
    # path (prompt + prefix, chunked prefill + prefix cache) and decoding
    # continues at sequence position len(prompt) + len(prefix) — the
    # position-keyed sampling chain then draws exactly the tokens the
    # uninterrupted stream would have drawn (bit-identical for a fixed
    # seed, trivially for greedy). Prefix tokens count toward max_tokens
    # and toward the presence/frequency penalty counts, exactly as if
    # this engine had generated them itself.
    prefix_tokens: tuple[int, ...] = ()


@dataclasses.dataclass
class EngineConfig:
    model: str = "debug-tiny"
    dtype: str = "bfloat16"
    max_decode_slots: int = 8
    page_size: int = 64
    num_pages: int = 512
    pages_per_slot: int = 32
    prefill_buckets: tuple[int, ...] = (64, 256, 1024)
    quantization: Optional[str] = None  # None | "int8" (weight-only)
    # multi-host pod group: coordinator broadcasts each step's inputs so
    # follower processes enter the same SPMD programs (engine/multihost.py)
    multihost: bool = False
    # async (pipelined) scheduling: keep up to async_depth decode steps in
    # flight, feeding each step's on-device sampled tokens straight into the
    # next launch; host copies are read by a dedicated harvester thread in
    # batched device_gets, so the ENGINE thread never blocks on device
    # work except for backpressure at full depth — admissions and their
    # prefills dispatch immediately (vLLM-style async scheduling, re-done
    # for JAX's dispatch model). Finishes/stop tokens are detected a
    # transfer-latency late; the speculative extra steps are harmless
    # (their writes land in pages that are only reused after
    # device-ordered completion). Works under multihost too: the packed
    # broadcast tells followers which device-resident token reference
    # feeds each merge.
    async_scheduling: bool = True
    async_depth: int = 2
    # device-queue pacing (0 = off): don't dispatch a decode step when the
    # estimated undone device work already exceeds this many step-times.
    # The pipeline cap (async_depth) bounds SPECULATION; this bounds the
    # DEVICE QUEUE — the thing a newly admitted request's prefill waits
    # behind. Set to ~(one-way dispatch latency / step time) + 1..2: big
    # enough that the device never starves, small enough that TTFT ≈ a
    # couple of step times + prefill + read latency.
    pace_target_steps: float = 0.0
    # async admission: up to this many same-bucket waiting requests prefill
    # together in one [K, bucket] call (padded to exactly 1 or admit_batch
    # rows so each bucket compiles two executables, not one per K)
    admit_batch: int = 4
    # admission control: submit() raises QueueFullError beyond this many
    # waiting requests (HTTP 429 upstream) — an unbounded queue lets a
    # burst pin memory and inflate TTFT without bound
    max_waiting: int = 256
    # prefix caching: full pages of a prompt already computed by an earlier
    # request are adopted instead of re-prefilled (page-level hash-chained
    # reuse — the vLLM-image capability, SURVEY §2.3 row 1); the remainder
    # prefills through the chunk path with history = the cached length
    prefix_caching: bool = True
    # multimodal: images per request the mm-prefill executable is compiled
    # for (requests with more are rejected at submit); the embeds buffer
    # is padded to this count, so raising it costs only prefill-input HBM
    max_images_per_request: int = 4
    # KV cache storage dtype: None => engine dtype; "int8" => per-token
    # quantized KV (halved decode-attention HBM traffic, doubled token
    # capacity; accuracy pinned by logit-tolerance tests). None also
    # falls through to env LLMK_KV_DTYPE ("" / "none" => off) so the
    # deployment chart can set it without CLI plumbing.
    kv_cache_dtype: Optional[str] = None
    # host-RAM offload tier (engine/cache.HostKVCache): finished and
    # preempted slots spill their full KV pages to a host-side LRU of
    # this many GB, keyed by (tenant, prefix digest); a returning session
    # whose prompt extends a spilled prefix re-uploads the pages and
    # skips straight to decode instead of re-prefilling. Requires
    # prefix_caching (the digest chain IS the addressing scheme).
    # None => env LLMK_KV_HOST_CACHE_GB; <= 0 disables.
    kv_host_cache_gb: Optional[float] = None
    # disaggregated serving role: "both" (default) serves prefill+decode
    # colocated; "prefill" replicas answer generation requests with a KV
    # handoff ticket (prompt ingested, first token sampled, pages spilled
    # to the host tier keyed by chained digest) instead of streaming;
    # "decode" replicas adopt handed-off pages and run the fused K-step
    # loop. The engine itself stays fully capable under every role — the
    # role gates SERVER behavior (openai_api) and deployment shape, so a
    # decode replica can always fall back to colocated serving (full
    # re-prefill) when a handoff goes missing. None => env LLMK_ROLE.
    role: Optional[str] = None
    # grammar-constrained decoding device-table capacities (static jit
    # shapes). A grammar whose tables exceed states/classes caps is
    # rejected at submit (400); distinct RESIDENT grammars beyond
    # max_grammars wait for a slot like page-pool pressure. The arrays
    # only exist once the first constrained request is admitted —
    # grammar-free serving compiles the exact pre-grammar executables.
    max_grammars: int = 4
    grammar_states: int = 4096
    grammar_classes: int = 512
    # decode KV write strategy: "dus" (default) | "scatter" |
    # "scatter-linear" | "fused" (opt-in until hardware-validated —
    # cache.py discusses the tradeoff). None => the LLMK_KV_WRITE env
    # default, resolved ONCE in __post_init__ — the strategy is part of
    # the engine's static config and baked into its executables, so env
    # mutation after construction has no effect (by design, documented)
    # and two engines in one process may use different strategies.
    kv_write: Optional[str] = None
    # watchdog: a device step that produces no completion within
    # max(watchdog_stall_s, 50 x recent step estimate) is declared stalled
    # — the engine sheds all work (EngineStallError -> "stalled" finishes,
    # wedged state, 503s upstream) instead of blocking forever in a
    # harvester wait. None => env LLMK_WATCHDOG_S (default 120); <= 0
    # disables.
    watchdog_stall_s: Optional[float] = None
    # multi-tenant LoRA (engine/adapters.py + ops/lora.py): (name, ref)
    # pairs of servable adapters. Requests pick one by name
    # (model=base:adapter upstream); adapter_slots bounds how many live in
    # the device stacks at once (LRU-recycled), adapter_rank is the stack
    # rank every adapter is padded to (rank > cap rejected at load), and
    # adapter_targets names the weights the stacks attach to. Empty
    # adapters => no stacks exist and every executable is byte-identical
    # to the pre-LoRA engine.
    adapters: tuple = ()
    adapter_slots: int = 4
    adapter_rank: int = 16
    adapter_targets: tuple = ("wq", "wk", "wv", "wo")
    # fused multi-step decode: one jitted dispatch runs this many decode
    # steps via lax.scan — sampling, penalties, stop-token detection, the
    # grammar FSM advance, and per-row early-exit masks all stay on device
    # (_decode_multi_packed_step); the harvester drains up to K packed
    # tokens per dispatch. Amortizes the per-dispatch host round trip
    # (ROADMAP item 5) and is the substrate the verify-k-tokens
    # speculative path lands on. None => env LLMK_DECODE_STEPS (default
    # 4). Forced to 1 under multihost until the broadcast protocol
    # carries the window. Streams are bit-identical to decode_steps=1
    # (same PRNG positions, same penalty-count evolution) — pinned by
    # tests/test_decode_multistep.py.
    decode_steps: Optional[int] = None
    # speculative decoding on the fused window (engine/speculation.py):
    # "ngram" drafts up to decode_steps-1 tokens per slot by prompt-lookup
    # over the request's own context; "draft" rolls out a small draft
    # model (draft_model: registry name or .gguf path). Drafted tokens
    # ride the packed window and the target scores all K positions in one
    # verify dispatch — greedy outputs are bit-identical to speculation
    # off (exact-match acceptance), seeded sampling matches through the
    # fold_in(base, seed)+position PRNG chain. None/"off" disables. Forced
    # off under multihost (the K=1 clamp leaves no draft room anyway).
    # Env: LLMK_SPECULATION / LLMK_DRAFT_MODEL.
    speculation: Optional[str] = None
    draft_model: Optional[str] = None
    # per-tenant QoS (engine/qos.py): admission runs deficit-weighted fair
    # queuing across tenants inside strict priority classes. qos_weights /
    # qos_priorities are (tenant, value) pairs (dicts normalize); unlisted
    # tenants get qos_default_weight / qos_default_priority. Starvation
    # aging: a lower-class head waiting > qos_starvation_s is served ahead
    # of higher classes (<= 0 disables). With no tenants configured and no
    # tenant-tagged submissions the queue degenerates to FIFO — every
    # request lands in the one default bucket — so single-tenant serving
    # (and K=1/K=4 decode parity) is byte-identical to the old deque.
    qos_weights: tuple = ()
    qos_priorities: tuple = ()
    qos_default_weight: float = 1.0
    qos_default_priority: str = "normal"
    qos_starvation_s: float = 5.0
    # goodput ledger (engine/ledger.py): per-request chip-time attribution
    # across prefill/decode/spec_waste/early_exit, MFU/MBU accounting, and
    # the step-time anomaly detector. None => env LLMK_LEDGER (default on).
    # Off restores the exact pre-ledger hot path (no per-dispatch booking).
    ledger: Optional[bool] = None
    # anomaly-triggered auto-profiling: when the ledger's EWMA + z-score
    # detector sees a sustained per-dispatch slowdown, the serving loop
    # captures ONE bounded profile (rate-limited by the cooldown). None =>
    # env LLMK_ANOMALY_PROFILE (default on while the ledger is on) /
    # LLMK_ANOMALY_Z (z-score threshold) / LLMK_ANOMALY_COOLDOWN_S.
    anomaly_profile: Optional[bool] = None
    anomaly_z: Optional[float] = None
    anomaly_cooldown_s: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        import os

        from llms_on_kubernetes_tpu.engine.cache import (
            KV_WRITE_STRATEGIES, default_kv_write_strategy,
        )

        if self.kv_write is None:
            self.kv_write = default_kv_write_strategy()
        if self.decode_steps is None:
            self.decode_steps = int(os.environ.get("LLMK_DECODE_STEPS", "4"))
        if self.decode_steps < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {self.decode_steps}")
        if self.multihost and self.decode_steps > 1:
            # followers mirror single-step MSG_DECODE programs; the packed
            # broadcast does not carry the window yet
            self.decode_steps = 1
        if self.speculation is None:
            self.speculation = os.environ.get("LLMK_SPECULATION") or None
        if self.draft_model is None:
            self.draft_model = os.environ.get("LLMK_DRAFT_MODEL") or None
        if self.speculation in ("off", "none", ""):
            self.speculation = None
        if self.speculation is None and self.draft_model is not None:
            self.speculation = "draft"  # a draft model implies the tier
        if self.speculation not in (None, "ngram", "draft"):
            raise ValueError(
                f"speculation must be one of None/'off'/'ngram'/'draft', "
                f"got {self.speculation!r}")
        if self.speculation == "draft" and self.draft_model is None:
            raise ValueError(
                "speculation='draft' requires draft_model (registry name "
                "or .gguf path)")
        if self.multihost and self.speculation is not None:
            # the K=1 clamp above leaves no draft room, and followers
            # could not mirror a variable-accept window — reject cleanly
            # rather than diverge
            self.speculation = None
        if self.watchdog_stall_s is None:
            self.watchdog_stall_s = float(
                os.environ.get("LLMK_WATCHDOG_S", "120"))
        if self.kv_cache_dtype is None:
            self.kv_cache_dtype = os.environ.get("LLMK_KV_DTYPE") or None
        if self.kv_cache_dtype in ("off", "none", ""):
            self.kv_cache_dtype = None
        if self.kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_cache_dtype must be None/'int8', got "
                f"{self.kv_cache_dtype!r}")
        if self.kv_host_cache_gb is None:
            self.kv_host_cache_gb = float(
                os.environ.get("LLMK_KV_HOST_CACHE_GB", "0"))
        if self.kv_host_cache_gb < 0:
            self.kv_host_cache_gb = 0.0
        if self.role is None:
            self.role = os.environ.get("LLMK_ROLE", "both").strip() or "both"
        if self.role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode', or 'both', got "
                f"{self.role!r}")
        if self.role == "prefill" and self.kv_host_cache_gb <= 0:
            raise ValueError(
                "role='prefill' requires kv_host_cache_gb > 0 — handoff "
                "tickets point decode replicas at pages spilled into the "
                "host tier")
        if self.multihost and self.role != "both":
            raise ValueError(
                "role is unsupported under multihost (the KV handoff "
                "rides the coordinator-local host tier)")
        _off = ("0", "false", "off", "no")
        if self.ledger is None:
            self.ledger = (os.environ.get("LLMK_LEDGER", "1")
                           .strip().lower() not in _off)
        if self.anomaly_profile is None:
            self.anomaly_profile = (os.environ.get("LLMK_ANOMALY_PROFILE", "1")
                                    .strip().lower() not in _off)
        if self.anomaly_z is None:
            self.anomaly_z = float(os.environ.get("LLMK_ANOMALY_Z", "4.0"))
        if self.anomaly_z <= 0:
            raise ValueError(
                f"anomaly_z must be > 0, got {self.anomaly_z}")
        if self.anomaly_cooldown_s is None:
            self.anomaly_cooldown_s = float(
                os.environ.get("LLMK_ANOMALY_COOLDOWN_S", "600"))
        if self.anomaly_cooldown_s < 0:
            self.anomaly_cooldown_s = 0.0
        if self.kv_write not in KV_WRITE_STRATEGIES:
            raise ValueError(
                f"kv_write must be one of {KV_WRITE_STRATEGIES}, "
                f"got {self.kv_write!r}")
        # grammar tables are int16 on device; ABSOLUTE (rebased) state and
        # class ids must fit, or the rebase in _ensure_grammar would wrap
        # silently and mask the wrong tokens
        if not 0 < self.grammar_states <= 32767:
            raise ValueError(
                f"grammar_states must be in (0, 32767], got "
                f"{self.grammar_states}")
        if not 0 < self.grammar_classes <= 32767:
            raise ValueError(
                f"grammar_classes must be in (0, 32767], got "
                f"{self.grammar_classes}")
        # normalize adapters to sorted (name, ref) pairs; names must be
        # usable inside OpenAI model strings ("base:adapter") and metric
        # label values
        if isinstance(self.adapters, dict):
            self.adapters = tuple(sorted(self.adapters.items()))
        else:
            self.adapters = tuple((str(n), str(r)) for n, r in self.adapters)
        seen_names: set = set()
        for name, _ref in self.adapters:
            if not name or ":" in name or "," in name or "=" in name \
                    or any(c.isspace() for c in name):
                raise ValueError(
                    f"adapter name {name!r} is invalid (no ':', ',', '=', "
                    f"whitespace, or empty)")
            if name in seen_names:
                raise ValueError(f"duplicate adapter name {name!r}")
            seen_names.add(name)
        if self.adapters:
            if self.adapter_slots < 1:
                raise ValueError(
                    f"adapter_slots must be >= 1 when adapters are "
                    f"configured, got {self.adapter_slots}")
            if self.adapter_rank < 1:
                raise ValueError(
                    f"adapter_rank must be >= 1, got {self.adapter_rank}")
        self.adapter_targets = tuple(self.adapter_targets)
        # normalize the QoS maps to sorted (tenant, value) pairs, same
        # convention as adapters (hashable config, deterministic order)
        from llms_on_kubernetes_tpu.engine.qos import MIN_WEIGHT, PRIORITIES
        if isinstance(self.qos_weights, dict):
            self.qos_weights = tuple(sorted(self.qos_weights.items()))
        self.qos_weights = tuple(
            (str(t), float(w)) for t, w in self.qos_weights)
        for tenant, w in self.qos_weights:
            if w < MIN_WEIGHT:
                raise ValueError(
                    f"qos weight for tenant {tenant!r} must be >= "
                    f"{MIN_WEIGHT}, got {w}")
        if isinstance(self.qos_priorities, dict):
            self.qos_priorities = tuple(sorted(self.qos_priorities.items()))
        self.qos_priorities = tuple(
            (str(t), str(p)) for t, p in self.qos_priorities)
        for tenant, p in self.qos_priorities:
            if p not in PRIORITIES:
                raise ValueError(
                    f"qos priority for tenant {tenant!r} must be one of "
                    f"{PRIORITIES}, got {p!r}")
        if self.qos_default_priority not in PRIORITIES:
            raise ValueError(
                f"qos_default_priority must be one of {PRIORITIES}, got "
                f"{self.qos_default_priority!r}")
        if self.qos_default_weight < MIN_WEIGHT:
            raise ValueError(
                f"qos_default_weight must be >= {MIN_WEIGHT}, got "
                f"{self.qos_default_weight}")

    @property
    def max_model_len(self) -> int:
        return self.page_size * self.pages_per_slot


@dataclasses.dataclass
class Request:
    id: str
    prompt: list[int]
    params: SamplingParams
    # multimodal: preprocessed pixels [n_images, H, W, C] float32; the
    # prompt carries matching image-soft-token runs (cfg.image_token_id)
    images: Optional[Any] = None
    # mrope models (Qwen3-VL): rope position = token index + this delta
    # for text continuation after images (set at mm admission)
    mrope_delta: int = 0
    # prefix-cache digest salt, computed ONCE at submit: b"" for text,
    # an image-bytes hash for cacheable multimodal prompts, None = skip
    cache_salt: Optional[bytes] = b""
    # resolved sampling seed (user's params.seed, or engine-drawn): the
    # request's sampled stream is fold(base_key, seed, position) — a pure
    # function of the request, never of batch composition or preemption
    seed: int = 0
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    # absolute time.monotonic() deadline (end-to-end budget threaded from
    # the client via router/API). Expired in the waiting queue => shed
    # without ever being admitted; expired in a slot => aborted, both with
    # finish_reason "timeout". None = no budget.
    deadline: Optional[float] = None
    # runtime state
    output: list[int] = dataclasses.field(default_factory=list)
    # per output token: (logprob, top_ids, top_logprobs) — recorded by
    # _emit before the token's event is delivered, so readers may index
    # it by token position for any delivered token
    output_logprobs: list = dataclasses.field(default_factory=list)
    slot: int = -1
    pending_token: int = -1        # sampled but KV not yet cached
    # grammar-constrained decoding: the request's row in the device class
    # table and its absolute start state (set at admission, -1 = none);
    # pending_fsm_state carries a host-replayed state the next decode
    # launch must force onto the device (resume-after-preemption)
    fsm_row: int = -1
    fsm_start: int = -1
    pending_fsm_state: Optional[int] = None
    # multi-tenant LoRA: the adapter NAME this request decodes with (None
    # = base model) and its pinned device slot in the LoRA stacks (-1
    # until admission acquires one; released at finish/preemption)
    adapter: Optional[str] = None
    adapter_slot: int = -1
    # per-tenant QoS: the fair-queue bucket this request bills to ("" =
    # the shared default bucket) and its resolved priority class — both
    # fixed at submit; the queue keys on them and preemption prefers
    # lower-priority victims
    tenant: str = ""
    priority: str = "normal"
    # disaggregated serving: True for a prefill-only handoff request (the
    # server answers with a ticket, not a stream) — its spilled pages are
    # drained to the host tier eagerly at finish even on a both-role
    # replica, so the decode replica's pull never races a lazy drain
    handoff: bool = False
    # goodput ledger: device milliseconds attributed to this request per
    # phase (prefill/decode/spec_waste/early_exit) — written by the engine
    # thread as dispatches harvest, surfaced in the OpenAI usage block,
    # the X-LLMK-Chip-Ms header, and the request's trace spans
    chip_ms: dict = dataclasses.field(default_factory=dict)
    finished: bool = False
    finish_reason: Optional[str] = None
    abort_reason: Optional[str] = None  # set by any thread; reaped by step()
    admitted_at: Optional[float] = None  # prefill dispatched (TTFT breakdown)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None  # terminal event recorded (_finish)
    # server-side trace sink (duck-typed: anything with .event(name, **kv));
    # the API layer points this at the request's Trace so engine-side
    # preemption/deadline/stall land on the distributed timeline. None for
    # direct engine use — the engine never requires it.
    trace: Optional[Any] = None
    events: "queue.SimpleQueue[tuple[list[int], bool, Optional[str]]]" = dataclasses.field(
        default_factory=queue.SimpleQueue
    )
    # optional push delivery: called from the ENGINE thread with each
    # event payload (the API server points this at its asyncio loop via
    # call_soon_threadsafe — a blocking queue.get per active stream would
    # park one executor thread per request and starve concurrency)
    on_event: Optional[Any] = None


@dataclasses.dataclass
class StepEvent:
    request: Request
    new_tokens: list[int]
    finished: bool
    finish_reason: Optional[str]


@dataclasses.dataclass
class InflightStep:
    """A launched-but-unharvested decode dispatch (async scheduling).
    With fused multi-step decode one dispatch carries a WINDOW of up to
    decode_steps tokens per slot; ``planned`` records how many tokens
    each slot's row was budgeted for (None => legacy single-step, 1
    per active slot)."""
    pack: Any                              # device packed result:
    #                                        [B, W] (K=1) or [K, B, W]
    toks: Any                              # device [B] sampled tokens (merge)
    active: list[tuple[int, Request]]      # (slot, request) snapshot at launch
    seq: int = -1                          # harvester sequence number
    planned: Optional[dict] = None         # slot -> tokens planned this window
    spec: bool = False                     # speculative verify dispatch:
    #                                        pack is (packs [K,B,W], accept [B])
    drafted: Optional[dict] = None         # slot -> drafted tokens this window
    launched_at: float = 0.0               # dispatch time (goodput ledger)


class _Harvester(threading.Thread):
    """Off-thread device->host reader for async scheduling.

    The engine thread pushes device SampleResults in dispatch order; this
    thread reads them with batched ``jax.device_get`` calls (one tunnel
    round trip amortized over everything completed) and marks them done.
    The engine thread polls ``is_done``/``get`` without ever blocking on
    device work — so a newly submitted request is admitted and its prefill
    dispatched IMMEDIATELY, instead of queueing behind a blocking read of
    ``async_depth`` in-flight decode steps (the round-2 gateway-TTFT
    finding). All engine state stays on the engine thread; this thread
    touches only device arrays and the results dict.

    Two classes of work:
    - decode steps (non-negative dense seqs): read oldest-first in small
      batches; done-ness is monotone (``is_done(s)`` implies every earlier
      step is done), so the engine harvests a strict prefix each step.
    - PRIORITY items (prefill results carrying first tokens, negative
      keys): jump the read queue and are read in their own small batches —
      a new request's TTFT must not wait behind a batch of decode-step
      reads it doesn't depend on."""

    def __init__(self, readers: Optional[int] = None,
                 batch: Optional[int] = None):
        import os
        super().__init__(daemon=True, name="engine-harvester")
        self._cv = threading.Condition()
        self._pending: "collections.deque[tuple[int, Any]]" = collections.deque()
        self._prio: "collections.deque[tuple[int, Any]]" = collections.deque()
        self._staged: dict[int, Any] = {}   # read but predecessors not done
        self._done: dict[int, Any] = {}     # steps (dense prefix) + priority
        # monotonic completion time per done key — when its device_get
        # landed. The goodput ledger segments busy time on these.
        self._done_t: dict[int, float] = {}
        self._done_upto = -1
        self._next_seq = 0                  # next step seq to mark done
        self._stopping = False
        # a device_get failure (tunnel drop, OOM surfacing on the read)
        # must surface on the ENGINE thread, not silently kill a reader —
        # otherwise every wait_done/wait_key blocks forever (observed as a
        # bench hang). First error wins; all waiters re-raise it.
        self._error: Optional[BaseException] = None
        # cumulative seconds this thread spent blocked in device_get —
        # the engine folds it into its kernel-vs-host attribution; plain
        # float += is safe: only this thread writes, readers tolerate a
        # slightly stale value
        self.device_time_s = 0.0
        # small batches + overlapped readers: one huge batched read would
        # couple every completion to the newest dispatch and mark done in
        # lumps; overlapping 2+ reads pipelines the tunnel RTT instead
        self._batch = batch if batch is not None else int(
            os.environ.get("LLMK_HARVEST_BATCH", "4"))
        self._readers = readers if readers is not None else int(
            os.environ.get("LLMK_HARVEST_READERS", "2"))
        self._extra: list[threading.Thread] = []

    def start(self) -> None:  # type: ignore[override]
        super().start()
        for i in range(self._readers - 1):
            t = threading.Thread(target=self.run, daemon=True,
                                 name=f"engine-harvester-{i + 1}")
            t.start()
            self._extra.append(t)

    def push(self, key: int, res: Any, priority: bool = False) -> None:
        _start_host_copy(res)  # transfer overlaps with device compute
        with self._cv:
            (self._prio if priority else self._pending).append((key, res))
            self._cv.notify_all()

    def run(self) -> None:
        while True:
            with self._cv:
                while not (self._pending or self._prio) and not self._stopping:
                    self._cv.wait()
                if self._stopping and not (self._pending or self._prio):
                    return
                if self._prio:
                    batch = list(self._prio)
                    self._prio.clear()
                    priority = True
                else:
                    n = min(max(1, self._batch), len(self._pending))
                    batch = [self._pending.popleft() for _ in range(n)]
                    priority = False
            try:
                # deterministic fault hooks (LLMK_FAULT=): a wedged device
                # read ("engine_stall" hangs here; the engine thread's
                # watchdog wait must fire) or a slow-but-live one
                # ("slow_step" delays each read)
                from llms_on_kubernetes_tpu import faults
                faults.inject_hang("engine_stall")
                faults.inject_delay("slow_step", 0.2)
                t0 = time.perf_counter()
                host = jax.device_get([r for _, r in batch])
                self.device_time_s += time.perf_counter() - t0
            except BaseException as e:  # noqa: BLE001 — must not die silent
                with self._cv:
                    if self._error is None:
                        self._error = e
                    self._cv.notify_all()
                return
            t_read = time.monotonic()
            with self._cv:
                if priority:
                    for (key, _), h in zip(batch, host):
                        self._done[key] = h
                        self._done_t[key] = t_read
                else:
                    for (seq, _), h in zip(batch, host):
                        self._staged[seq] = (h, t_read)
                    # done-ness stays a dense seq prefix even with
                    # overlapped readers finishing out of order
                    while self._next_seq in self._staged:
                        h, t = self._staged.pop(self._next_seq)
                        self._done[self._next_seq] = h
                        self._done_t[self._next_seq] = t
                        self._done_upto = self._next_seq
                        self._next_seq += 1
                self._cv.notify_all()

    def _check_error(self) -> None:
        if self._error is not None:
            # re-raise the ORIGINAL exception (same type): callers up the
            # stack classify transient transport errors by type+message
            # (bench.py retries JaxRuntimeError INTERNAL/UNAVAILABLE)
            raise self._error

    def is_done(self, seq: int) -> bool:
        self._check_error()
        return seq <= self._done_upto

    def key_done(self, key: int) -> bool:
        self._check_error()
        return key in self._done

    def get(self, key: int) -> Any:
        with self._cv:
            return self._done[key]

    def done_time(self, key: int) -> float:
        """Monotonic time key's device_get completed (ledger segmenting);
        falls back to now for keys whose stamp was already discarded."""
        with self._cv:
            return self._done_t.get(key, time.monotonic())

    def wait_done(self, seq: int, wake: Optional[threading.Event] = None,
                  timeout_s: Optional[float] = None) -> None:
        """Block until step ``seq`` is done — or, if ``wake`` is given,
        until it is set (a new submission wants admission NOW — submit()
        pokes this cv; the caller re-enters its loop and the next step()
        admits before waiting again). With ``timeout_s`` (the engine's
        watchdog budget) raises EngineStallError if the step is still
        incomplete at the deadline."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cv:
            while self._done_upto < seq:
                self._check_error()
                if wake is not None and wake.is_set():
                    return
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise EngineStallError(
                        f"device step {seq} produced no completion within "
                        f"{timeout_s:.1f}s watchdog budget")
                self._cv.wait(timeout=min(remaining, 1.0))

    def poke(self) -> None:
        """Wake any wait_done(wake=...) waiter (called from submit())."""
        with self._cv:
            self._cv.notify_all()

    def wait_key(self, key: int, timeout_s: Optional[float] = None) -> None:
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cv:
            while key not in self._done:
                self._check_error()
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise EngineStallError(
                        f"prefill result {key} did not arrive within "
                        f"{timeout_s:.1f}s watchdog budget")
                self._cv.wait(timeout=min(remaining, 1.0))

    def discard_upto(self, seq: int) -> None:
        with self._cv:
            for s in [s for s in self._done if 0 <= s <= seq]:
                del self._done[s]
                self._done_t.pop(s, None)

    def discard_key(self, key: int) -> None:
        with self._cv:
            self._done.pop(key, None)
            self._done_t.pop(key, None)

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()


def _merge_tokens(last_toks, src, vals, prefill_toks, prefill_row):
    """Builds the decode input vector on device: per slot take the previous
    in-flight step's sampled token (src 0), a host-known value (src 1), or
    the token sampled by this step's prefill at row prefill_row (src 2)."""
    return jnp.where(src == 0, last_toks,
                     jnp.where(src == 1, vals, prefill_toks[prefill_row]))


def _count_decode_tokens(counts, tokens, active):
    """counts[b, tokens[b]] += active[b] — unrolled DUS (in-place; an HLO
    scatter would copy the [B, V] buffer, see cache.write_tokens)."""
    B = counts.shape[0]
    active = active.astype(counts.dtype)
    for b in range(B):
        cur = jax.lax.dynamic_slice(counts, (b, tokens[b]), (1, 1))
        counts = jax.lax.dynamic_update_slice(
            counts, cur + active[b], (b, tokens[b]))
    return counts


def _rebuild_count_rows(counts, tokens, slots, history, prompt_len, lengths,
                        reset):
    """Rebuild per-slot output-token counts from a prefill/chunk batch.

    Row semantics: a request's FIRST chunk (reset[r] != 0 — history may be
    nonzero when a cached prefix was adopted) resets the slot's counts; a
    continuation accumulates. Only tokens at global positions >=
    prompt_len count (penalties cover OUTPUT tokens — vLLM semantics);
    that's non-empty exactly for resumed (preempted) re-prefills, whose
    prompt+output tokens replay through this path."""
    K, T = tokens.shape
    V = counts.shape[1]
    t_iota = jnp.arange(T, dtype=jnp.int32)
    for r in range(K):
        out_mask = ((history[r] + t_iota >= prompt_len[r])
                    & (t_iota < lengths[r])).astype(counts.dtype)
        contrib = jnp.zeros((V,), counts.dtype).at[tokens[r]].add(
            out_mask, mode="drop")
        existing = jax.lax.dynamic_slice(counts, (slots[r], 0), (1, V))[0]
        row = jnp.where(reset[r] != 0, 0, existing) + contrib
        # idle/padded rows (lengths 0) keep their slot's counts untouched
        row = jnp.where(lengths[r] > 0, row, existing)
        counts = jax.lax.dynamic_update_slice(
            counts, row[None], (slots[r], 0))
    return counts


# --- packed single-upload step variants (async scheduling) -----------------
# Over a remote-device tunnel every host->device transfer costs a round
# trip; shipping the scheduler's small arrays separately costs ~35 ms per
# step vs ~5 ms for one packed int32 array (floats ride along bitcast).
# The token merge and the PRNG fold_in also move inside the executable so a
# decode step is exactly ONE upload + ONE dispatch.

# OpenAI logit_bias: per-request (token id, bias) pairs ride the packed
# rows as LOGIT_BIAS_SLOTS id columns + LOGIT_BIAS_SLOTS value columns
# (float bits), padding id -1 => dropped by the on-device scatter. Maps
# with more entries are rejected at submit() (400 upstream) — the column
# budget is a hard bound, like MAX_CANDIDATES for top_k.
LOGIT_BIAS_SLOTS = 32


# --- grammar-constrained decoding (engine/grammar.py) ----------------------
# The per-slot FSM lives ON DEVICE so constrained requests ride the async
# pipeline: each packed step masks the logits with the allowed-token set of
# the slot's current FSM state and advances the state by the token it
# samples — no host round trip. The packed rows carry:
#   prefill/chunk: [fsm_row, fsm_init]   row in the class table (-1 = this
#     row samples unconstrained) and the absolute start state to assume
#     before sampling (set at admission; -1 for resumed rows, whose decode
#     overrides with the host-replayed state)
#   decode:        [fsm_row, fsm_set, fsm_val]   fsm_set=1 overrides the
#     device state with fsm_val before masking (resume-after-preemption)
# The tables (class_of [G, V] int16, trans [S, C] int16) are device arrays
# rebuilt only when the RESIDENT GRAMMAR SET changes (admission-time, never
# per-step). fsm=None compiles the exact pre-grammar executables — serving
# without grammars pays nothing.


def _fsm_apply(fsm, g_rows, states):
    """Per-row mask + transition lookup: one [R, V] gather serves both.

    Returns (allowed [R, V] bool, nxt_all [R, V] int — the state each
    token would lead to, -1 = token not allowed). Rows with g_rows < 0
    are unconstrained (allowed all-True)."""
    _state_arr, class_of, trans = fsm
    classes = class_of[jnp.maximum(g_rows, 0)]             # [R, V] int16
    row_trans = trans[jnp.maximum(states, 0)]              # [R, C] int16
    nxt_all = jnp.take_along_axis(
        row_trans, classes.astype(jnp.int32), axis=1)      # [R, V]
    constrained = (g_rows >= 0) & (states >= 0)
    allowed = jnp.where(constrained[:, None], nxt_all >= 0, True)
    return allowed, nxt_all, constrained


def _fsm_next(nxt_all, tokens):
    """State after emitting the sampled token ([R] int32)."""
    return jnp.take_along_axis(
        nxt_all, tokens.astype(jnp.int32)[:, None], axis=1)[:, 0].astype(
        jnp.int32)


def _unpack_bias(packed, base: int):
    ids = packed[:, base:base + LOGIT_BIAS_SLOTS]
    vals = jax.lax.bitcast_convert_type(
        packed[:, base + LOGIT_BIAS_SLOTS:base + 2 * LOGIT_BIAS_SLOTS],
        jnp.float32)
    return ids, vals


def _pack_bias(packed: np.ndarray, row: int, base: int, params) -> None:
    packed[row, base:base + LOGIT_BIAS_SLOTS] = -1
    for j, (tid, bv) in enumerate(params.logit_bias):
        packed[row, base + j] = tid
        packed[row, base + LOGIT_BIAS_SLOTS + j] = np.float32(bv).view(np.int32)


# on-device stop-token detection (fused multi-step decode): each row
# carries its request's stop_token_ids so the window's early-exit mask can
# kill the row the moment one is sampled; -1 pads unused slots. More than
# STOP_SLOTS stop ids are rejected at submit() — the column budget is a
# hard bound, like LOGIT_BIAS_SLOTS.
STOP_SLOTS = 8

# packed decode columns: 0 lengths, 1 src, 2 vals, 3 top_k, 4 temps(bits),
# 5 top_p(bits), 6 seed, 7 prefill_row, 8 presence(bits),
# 9 frequency(bits), 10 pos_delta (mrope), 11 adapter_slot (-1 = base),
# 12-14 fsm (row, set, val), 15 window budget (planned alive iterations —
# multi-step decode only, 0/ignored for K=1), 16.. stop-token ids
# (STOP_SLOTS, -1 padded), then logit_bias ids/vals, then page_table
_ADP_DEC = 11
_FSM_DEC = 12
_BUD_DEC = 15
_STOP_DEC = 16
_BIAS_DEC = _STOP_DEC + STOP_SLOTS
_DEC_COLS = _BIAS_DEC + 2 * LOGIT_BIAS_SLOTS


def _decode_packed_step(params, cfg, packed, last_toks, prefill_toks,
                        k_pages, v_pages, counts, base_key, fsm=None):
    lengths = packed[:, 0]
    src, vals = packed[:, 1], packed[:, 2]
    top_ks = packed[:, 3]
    temps = jax.lax.bitcast_convert_type(packed[:, 4], jnp.float32)
    top_ps = jax.lax.bitcast_convert_type(packed[:, 5], jnp.float32)
    seeds = packed[:, 6]
    prefill_row = packed[:, 7]
    presence = jax.lax.bitcast_convert_type(packed[:, 8], jnp.float32)
    frequency = jax.lax.bitcast_convert_type(packed[:, 9], jnp.float32)
    pos_delta = packed[:, 10]
    adapter_idx = packed[:, _ADP_DEC]
    bias = _unpack_bias(packed, _BIAS_DEC)
    page_table = packed[:, _DEC_COLS:]

    tokens = _merge_tokens(last_toks, src, vals, prefill_toks, prefill_row)
    # the input token is always a previously-sampled OUTPUT token: count
    # it before sampling so this step's draw sees it
    counts = _count_decode_tokens(counts, tokens, lengths > 0)
    logits, k_pages, v_pages = forward_decode(
        params, cfg, tokens, lengths, k_pages, v_pages, page_table,
        pos_delta=pos_delta, adapter_idx=adapter_idx,
    )
    keys = _slot_keys(base_key, seeds, lengths)
    allowed = nxt_all = new_state = None
    if fsm is not None:
        g_rows = packed[:, _FSM_DEC]
        base = jnp.where(packed[:, _FSM_DEC + 1] == 1,
                         packed[:, _FSM_DEC + 2], fsm[0])
        allowed, nxt_all, constrained = _fsm_apply(fsm, g_rows, base)
    res = sample(logits, keys, temps, top_ks, top_ps,
                 penalties=(presence, frequency, counts), bias=bias,
                 allowed=allowed)
    if fsm is not None:
        new_state = jnp.where(constrained & (lengths > 0),
                              _fsm_next(nxt_all, res.tokens), base)
    return res.host_pack(), res.tokens, k_pages, v_pages, counts, new_state


def _decode_multi_packed_step(params, cfg, K, packed, last_toks,
                              prefill_toks, k_pages, v_pages, counts,
                              base_key, fsm=None):
    """Fused multi-step decode: ONE dispatch runs K sampling steps via
    lax.scan, returning the K packed host rows stacked [K, B, W].

    Parity with K chained _decode_packed_step calls is exact: iteration j
    samples at sequence position lengths0 + j (same PRNG fold_in), counts
    its input token before sampling (same penalty evolution), and feeds
    its sampled token straight into iteration j+1's merge. Per-row
    early-exit: a row whose sampled token hits one of its stop ids — or
    whose planned budget (_BUD_DEC) runs out — is MASKED (lengths 0) for
    the remainder of the window, not recomputed: its KV writes divert to
    the trash page (cache.write_tokens pos<0), its counts stop
    accumulating, and its input token freezes so the host-side replay
    stays deterministic. The host (_emit) remains authoritative for
    finishes — the device mask can only under-run, never over-run, the
    stream. Grammar rows ride the loop: the FSM state is scan carry,
    masked+advanced per iteration exactly as the single-step path."""
    lengths0 = packed[:, 0]
    src, vals = packed[:, 1], packed[:, 2]
    top_ks = packed[:, 3]
    temps = jax.lax.bitcast_convert_type(packed[:, 4], jnp.float32)
    top_ps = jax.lax.bitcast_convert_type(packed[:, 5], jnp.float32)
    seeds = packed[:, 6]
    prefill_row = packed[:, 7]
    presence = jax.lax.bitcast_convert_type(packed[:, 8], jnp.float32)
    frequency = jax.lax.bitcast_convert_type(packed[:, 9], jnp.float32)
    pos_delta = packed[:, 10]
    adapter_idx = packed[:, _ADP_DEC]
    budget = packed[:, _BUD_DEC]
    stop_ids = packed[:, _STOP_DEC:_STOP_DEC + STOP_SLOTS]
    bias = _unpack_bias(packed, _BIAS_DEC)
    page_table = packed[:, _DEC_COLS:]

    toks0 = _merge_tokens(last_toks, src, vals, prefill_toks, prefill_row)
    if fsm is not None:
        g_rows = packed[:, _FSM_DEC]
        state0 = jnp.where(packed[:, _FSM_DEC + 1] == 1,
                           packed[:, _FSM_DEC + 2], fsm[0])
    else:
        state0 = jnp.zeros_like(lengths0)
    alive0 = (lengths0 > 0) & (budget > 0)

    def body(carry, j):
        cur, alive, state, k_pages, v_pages, counts = carry
        lengths = jnp.where(alive, lengths0 + j, 0)
        # the input token is always a previously-sampled OUTPUT token:
        # count it before sampling so this iteration's draw sees it
        counts = _count_decode_tokens(counts, cur, lengths > 0)
        logits, k_pages, v_pages = forward_decode(
            params, cfg, cur, lengths, k_pages, v_pages, page_table,
            pos_delta=pos_delta, adapter_idx=adapter_idx,
        )
        keys = _slot_keys(base_key, seeds, lengths)
        allowed = nxt_all = constrained = None
        if fsm is not None:
            allowed, nxt_all, constrained = _fsm_apply(fsm, g_rows, state)
        res = sample(logits, keys, temps, top_ks, top_ps,
                     penalties=(presence, frequency, counts), bias=bias,
                     allowed=allowed)
        new_toks = jnp.where(alive, res.tokens, cur)
        if fsm is not None:
            state = jnp.where(constrained & alive,
                              _fsm_next(nxt_all, res.tokens), state)
        stopped = ((stop_ids >= 0)
                   & (stop_ids == res.tokens[:, None])).any(axis=1)
        alive = alive & ~stopped & (j + 1 < budget)
        return (new_toks, alive, state, k_pages, v_pages, counts), \
            res.host_pack()

    carry0 = (toks0, alive0, state0, k_pages, v_pages, counts)
    (toks, _alive, state, k_pages, v_pages, counts), packs = jax.lax.scan(
        body, carry0, jnp.arange(K, dtype=jnp.int32))
    new_state = state if fsm is not None else None
    return packs, toks, k_pages, v_pages, counts, new_state


def _decode_spec_packed_step(params, cfg, K, packed, k_pages, v_pages,
                             counts, base_key, fsm=None):
    """Speculative verify dispatch: ONE forward pass scores a window of
    [committed token, K-1 drafted tokens] per slot (forward_verify), then
    K sampling iterations run over the precomputed logits — no further
    model dispatches. Returns ((packs [K, B, W], accept [B]), toks, ...):
    ``accept`` is the per-row count of VALID sampled tokens, which the
    harvest consumes instead of the planned budget.

    Parity with the fused scan (_decode_multi_packed_step) — and therefore
    with K=1 — is exact under greedy decoding and under seeded sampling:
    iteration j sees the SAME logits (forward_verify is the same chunk
    attention the sequential path produces position-by-position, pinned
    bit-identical by tests/test_speculation.py), the same PRNG key
    (_slot_keys folds base+seed+position), and the same penalty-count
    evolution. The only new exit condition is draft mismatch: iteration
    j's sampled token must equal draft j for iteration j+1's logits
    (conditioned on draft j) to be valid — exact-match acceptance, i.e.
    standard greedy speculative decoding. Rejected suffixes already wrote
    KV, but the next dispatch starts at the accepted length and overwrites
    them in place (the PR-8 tail-discard contract); page COUNTS never
    include rejected tokens because the host advances slot_len only for
    accepted ones.

    The packed layout appends K-1 draft columns AFTER the page table:
    [..., _DEC_COLS + pages_per_slot) is the page table, the trailing K-1
    columns are drafts (-1 = none; a row's drafts are prefix-contiguous).
    Spec dispatches launch only with host-known input tokens (src == 1 by
    construction), so last_toks/prefill_toks merging is unnecessary."""
    D = K - 1
    lengths0 = packed[:, 0]
    top_ks = packed[:, 3]
    temps = jax.lax.bitcast_convert_type(packed[:, 4], jnp.float32)
    top_ps = jax.lax.bitcast_convert_type(packed[:, 5], jnp.float32)
    seeds = packed[:, 6]
    presence = jax.lax.bitcast_convert_type(packed[:, 8], jnp.float32)
    frequency = jax.lax.bitcast_convert_type(packed[:, 9], jnp.float32)
    pos_delta = packed[:, 10]
    adapter_idx = packed[:, _ADP_DEC]
    budget = packed[:, _BUD_DEC]
    stop_ids = packed[:, _STOP_DEC:_STOP_DEC + STOP_SLOTS]
    bias = _unpack_bias(packed, _BIAS_DEC)
    page_table = packed[:, _DEC_COLS:packed.shape[1] - D]
    drafts = packed[:, packed.shape[1] - D:]                       # [B, D]

    toks0 = packed[:, 2]                                  # src==1 host value
    if fsm is not None:
        g_rows = packed[:, _FSM_DEC]
        state0 = jnp.where(packed[:, _FSM_DEC + 1] == 1,
                           packed[:, _FSM_DEC + 2], fsm[0])
    else:
        state0 = jnp.zeros_like(lengths0)
    alive0 = (lengths0 > 0) & (budget > 0)

    # verify window: committed token + drafts; write length w covers only
    # prefix-contiguous drafts and never exceeds the planned page budget
    has = jnp.cumprod((drafts >= 0).astype(jnp.int32), axis=1)     # [B, D]
    n_drafts = has.sum(axis=1)
    w = jnp.where(alive0, jnp.minimum(budget, 1 + n_drafts), 0)
    verify = jnp.concatenate(
        [toks0[:, None], jnp.maximum(drafts, 0)], axis=1)          # [B, K]
    history = jnp.maximum(lengths0 - 1, 0)
    logits_all, k_pages, v_pages = forward_verify(
        params, cfg, verify, history, w, k_pages, v_pages, page_table,
        pos_delta=pos_delta, adapter_idx=adapter_idx,
    )

    cur, alive, state = toks0, alive0, state0
    accept = jnp.zeros_like(lengths0)
    packs = []
    for j in range(K):
        lengths = jnp.where(alive, lengths0 + j, 0)
        # the input token is always a previously-committed OUTPUT token:
        # count it before sampling so this iteration's draw sees it
        counts = _count_decode_tokens(counts, cur, lengths > 0)
        keys = _slot_keys(base_key, seeds, lengths)
        allowed = nxt_all = constrained = None
        if fsm is not None:
            allowed, nxt_all, constrained = _fsm_apply(fsm, g_rows, state)
        res = sample(logits_all[:, j], keys, temps, top_ks, top_ps,
                     penalties=(presence, frequency, counts), bias=bias,
                     allowed=allowed)
        new_toks = jnp.where(alive, res.tokens, cur)
        if fsm is not None:
            state = jnp.where(constrained & alive,
                              _fsm_next(nxt_all, res.tokens), state)
        accept = accept + alive.astype(accept.dtype)
        packs.append(res.host_pack())
        stopped = ((stop_ids >= 0)
                   & (stop_ids == res.tokens[:, None])).any(axis=1)
        alive = alive & ~stopped & (j + 1 < budget)
        if j < D:
            # iteration j+1's logits were conditioned on draft j: they are
            # valid only if the sampled token exactly matches the draft
            alive = alive & (res.tokens == drafts[:, j]) & (has[:, j] > 0)
        cur = new_toks
    new_state = state if fsm is not None else None
    return ((jnp.stack(packs), accept), cur, k_pages, v_pages, counts,
            new_state)


# packed prefill columns: 0 lengths, 1 top_k, 2 temps(bits), 3 top_p(bits),
# 4 seed, 5 presence(bits), 6 frequency(bits), 7 slot, 8 prompt_len,
# 9 adapter_slot (-1 = base), 10-11 fsm (row, init), 12.. logit_bias
# ids/vals, then page_table
_ADP_PRE = 9
_FSM_PRE = 10
_BIAS_PRE = 12
_PRE_COLS = _BIAS_PRE + 2 * LOGIT_BIAS_SLOTS


def _fsm_scatter(fsm, g_rows, init, nxt_all, tokens, lengths, slots):
    """Prefill/chunk/mm per-slot state scatter. Rows write their slot's
    FSM state only when they are constrained FRESH starts (fsm_row >= 0
    and fsm_init >= 0) and real (length > 0); everything else leaves the
    slot's device state alone (resumed rows are overridden by their first
    decode's fsm_set instead)."""
    nxt = _fsm_next(nxt_all, tokens)
    write = (g_rows >= 0) & (init >= 0) & (lengths > 0)
    slot_eff = jnp.where(write, slots, fsm[0].shape[0])  # OOB => dropped
    return fsm[0].at[slot_eff].set(nxt, mode="drop")


def _prefill_mm_packed_step(params, cfg, tokens, packed, img_embeds,
                            deepstack, pos3, k_pages, v_pages, counts,
                            base_key, fsm=None):
    """Multimodal prefill ([1, bucket]): image soft-token embeddings are
    substituted inside forward_prefill_mm; sampling/penalties identical
    to the text prefill. ``deepstack``/``pos3`` are None for gemma-3 and
    carry the DeepStack features / 3-axis mrope positions for Qwen3-VL."""
    from llms_on_kubernetes_tpu.models.decoder import forward_prefill_mm

    lengths = packed[:, 0]
    top_ks = packed[:, 1]
    temps = jax.lax.bitcast_convert_type(packed[:, 2], jnp.float32)
    top_ps = jax.lax.bitcast_convert_type(packed[:, 3], jnp.float32)
    seeds = packed[:, 4]
    presence = jax.lax.bitcast_convert_type(packed[:, 5], jnp.float32)
    frequency = jax.lax.bitcast_convert_type(packed[:, 6], jnp.float32)
    slots = packed[:, 7]
    prompt_len = packed[:, 8]
    adapter_idx = packed[:, _ADP_PRE]
    bias = _unpack_bias(packed, _BIAS_PRE)
    page_table = packed[:, _PRE_COLS:]

    counts = _rebuild_count_rows(
        counts, tokens, slots, jnp.zeros_like(lengths), prompt_len, lengths,
        jnp.ones_like(lengths))
    logits, k_pages, v_pages = forward_prefill_mm(
        params, cfg, tokens, lengths, k_pages, v_pages, page_table,
        img_embeds, deepstack=deepstack, pos3=pos3, prompt_len=prompt_len,
        adapter_idx=adapter_idx,
    )
    keys = _slot_keys(base_key, seeds, lengths)
    allowed = nxt_all = new_state = None
    if fsm is not None:
        g_rows, init = packed[:, _FSM_PRE], packed[:, _FSM_PRE + 1]
        allowed, nxt_all, _ = _fsm_apply(fsm, g_rows, init)
    res = sample(logits, keys, temps, top_ks, top_ps,
                 penalties=(presence, frequency, counts[slots]), bias=bias,
                 allowed=allowed)
    if fsm is not None:
        new_state = _fsm_scatter(fsm, g_rows, init, nxt_all, res.tokens,
                                 lengths, slots)
    return res.host_pack(), res.tokens, k_pages, v_pages, counts, new_state


def _prefill_packed_step(params, cfg, tokens, packed, k_pages, v_pages,
                         counts, base_key, fsm=None):
    lengths = packed[:, 0]
    top_ks = packed[:, 1]
    temps = jax.lax.bitcast_convert_type(packed[:, 2], jnp.float32)
    top_ps = jax.lax.bitcast_convert_type(packed[:, 3], jnp.float32)
    seeds = packed[:, 4]
    presence = jax.lax.bitcast_convert_type(packed[:, 5], jnp.float32)
    frequency = jax.lax.bitcast_convert_type(packed[:, 6], jnp.float32)
    slots = packed[:, 7]
    prompt_len = packed[:, 8]
    adapter_idx = packed[:, _ADP_PRE]
    bias = _unpack_bias(packed, _BIAS_PRE)
    page_table = packed[:, _PRE_COLS:]

    counts = _rebuild_count_rows(
        counts, tokens, slots, jnp.zeros_like(lengths), prompt_len, lengths,
        jnp.ones_like(lengths))
    logits, k_pages, v_pages = forward_prefill(
        params, cfg, tokens, lengths, k_pages, v_pages, page_table,
        adapter_idx=adapter_idx,
    )
    keys = _slot_keys(base_key, seeds, lengths)
    row_counts = counts[slots]
    allowed = nxt_all = new_state = None
    if fsm is not None:
        g_rows, init = packed[:, _FSM_PRE], packed[:, _FSM_PRE + 1]
        allowed, nxt_all, _ = _fsm_apply(fsm, g_rows, init)
    res = sample(logits, keys, temps, top_ks, top_ps,
                 penalties=(presence, frequency, row_counts), bias=bias,
                 allowed=allowed)
    if fsm is not None:
        new_state = _fsm_scatter(fsm, g_rows, init, nxt_all, res.tokens,
                                 lengths, slots)
    return res.host_pack(), res.tokens, k_pages, v_pages, counts, new_state


# packed chunk columns: 0 chunk_len, 1 history, 2 top_k, 3 temps(bits),
# 4 top_p(bits), 5 seed, 6 presence(bits), 7 frequency(bits), 8 slot,
# 9 prompt_len, 10 reset (first chunk of the request — history may be
# nonzero when a cached prefix was adopted), 11 pos_delta (mrope: a
# cache-hit Qwen3-VL remainder replays through this path with rope
# positions shifted by the request's mrope delta), 12 adapter_slot
# (-1 = base), 13-14 fsm (row, init — set only on the FINAL chunk, whose
# sample is the first real token), 15.. logit_bias ids/vals, then
# page_table. Sampling position is the TOTAL length (history + chunk_len)
# so a chunked prompt draws exactly the tokens a one-shot prefill of the
# same prompt would.
_ADP_CHK = 12
_FSM_CHK = 13
_BIAS_CHK = 15
_CHK_COLS = _BIAS_CHK + 2 * LOGIT_BIAS_SLOTS


def _chunk_packed_step(params, cfg, tokens, packed, k_pages, v_pages,
                       counts, base_key, fsm=None):
    lengths = packed[:, 0]
    history = packed[:, 1]
    top_ks = packed[:, 2]
    temps = jax.lax.bitcast_convert_type(packed[:, 3], jnp.float32)
    top_ps = jax.lax.bitcast_convert_type(packed[:, 4], jnp.float32)
    seeds = packed[:, 5]
    presence = jax.lax.bitcast_convert_type(packed[:, 6], jnp.float32)
    frequency = jax.lax.bitcast_convert_type(packed[:, 7], jnp.float32)
    slots = packed[:, 8]
    prompt_len = packed[:, 9]
    reset = packed[:, 10]
    pos_delta = packed[:, 11]
    adapter_idx = packed[:, _ADP_CHK]
    bias = _unpack_bias(packed, _BIAS_CHK)
    page_table = packed[:, _CHK_COLS:]

    counts = _rebuild_count_rows(
        counts, tokens, slots, history, prompt_len, lengths, reset)
    logits, k_pages, v_pages = forward_chunk(
        params, cfg, tokens, history, lengths, k_pages, v_pages, page_table,
        pos_delta=pos_delta, adapter_idx=adapter_idx,
    )
    keys = _slot_keys(base_key, seeds, history + lengths)
    allowed = nxt_all = new_state = None
    if fsm is not None:
        g_rows, init = packed[:, _FSM_CHK], packed[:, _FSM_CHK + 1]
        allowed, nxt_all, _ = _fsm_apply(fsm, g_rows, init)
    res = sample(logits, keys, temps, top_ks, top_ps,
                 penalties=(presence, frequency, counts[slots]), bias=bias,
                 allowed=allowed)
    if fsm is not None:
        new_state = _fsm_scatter(fsm, g_rows, init, nxt_all, res.tokens,
                                 lengths, slots)
    return res.host_pack(), res.tokens, k_pages, v_pages, counts, new_state


def _spill_gather_pages(k_pages, v_pages, flat_idx):
    """Gather m spilling pages' bytes across all layers for the host tier:
    ``flat_idx`` [m, L] holds each page's flat pool index per layer
    (l * P + page_id). Returns (k [n_kv, m, L, page, d], v, k_scale
    [n_kv, m, L, page] | None, v_scale) — raw pool bytes, so the later
    re-upload round-trips exactly (no requantize, no dtype change)."""
    k = jnp.take(k_pages.data, flat_idx, axis=1)
    v = jnp.take(v_pages.data, flat_idx, axis=1)
    ks = jnp.take(k_pages.scale, flat_idx, axis=1) if k_pages.quantized else None
    vs = jnp.take(v_pages.scale, flat_idx, axis=1) if v_pages.quantized else None
    return k, v, ks, vs


def _upload_scatter_pages(k_pages, v_pages, flat_idx, k, v, ks, vs):
    """Inverse of _spill_gather_pages: splice m host-cached pages back
    into freshly allocated pool pages (pools donated — in place)."""
    from llms_on_kubernetes_tpu.engine.cache import KVPool

    kd = k_pages.data.at[:, flat_idx].set(k)
    vd = v_pages.data.at[:, flat_idx].set(v)
    ksc, vsc = k_pages.scale, v_pages.scale
    if ks is not None:
        ksc = ksc.at[:, flat_idx].set(ks)
        vsc = vsc.at[:, flat_idx].set(vs)
    return KVPool(kd, ksc), KVPool(vd, vsc)


def _start_host_copy(pack) -> None:
    """Begin async device->host transfer of a step's packed result (a
    device array, or a tuple of them for spec steps: (packs, accept))."""
    for arr in pack if isinstance(pack, (tuple, list)) else (pack,):
        try:
            arr.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass


def _lp_entry(host_res, row: int) -> tuple:
    """(logprob, top_ids, top_logprobs) for one row of a HostSample."""
    return (float(host_res.logprobs[row]),
            host_res.top_ids[row].tolist(),
            host_res.top_logprobs[row].tolist())


def _slot_keys(base_key, seeds, lengths):
    """Per-slot PRNG keys: fold(base, request seed, stream position). The
    position is `lengths` — for both prefill and decode it equals the
    sampled token's sequence position, so a preempted-and-resumed request
    draws exactly the tokens it would have drawn uninterrupted."""
    return jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.fold_in(base_key, s), p)
    )(seeds, lengths)


class Engine:
    """Multi-request continuous-batching engine for one model."""

    def __init__(
        self,
        engine_config: EngineConfig,
        model_config: Optional[ModelConfig] = None,
        params: Optional[Params] = None,
        mesh=None,
        model_dir: Optional[str] = None,
        weights_preload=None,
    ):
        from llms_on_kubernetes_tpu.ops.quant import SUPPORTED_QUANTIZATIONS

        self.config = engine_config
        if engine_config.quantization not in SUPPORTED_QUANTIZATIONS:
            raise ValueError(
                f"unknown quantization {engine_config.quantization!r} "
                f"(supported: {[q for q in SUPPORTED_QUANTIZATIONS if q]})"
            )
        self.model_config = model_config or get_config(engine_config.model)
        cfg = self.model_config
        self.mesh = mesh
        from llms_on_kubernetes_tpu.parallel.mesh import AXIS_SEQ, set_active_mesh

        if mesh is not None:
            sp = int(mesh.shape.get(AXIS_SEQ, 1))
            bad = [b for b in engine_config.prefill_buckets if b % sp != 0]
            if sp > 1 and bad:
                raise ValueError(
                    f"prefill buckets {bad} not divisible by the seq-parallel "
                    f"ring size {sp} (ring attention shards the bucket)"
                )
            if sp > 1 and engine_config.num_pages % sp != 0:
                # the flat pool axis shards over seq at page granularity
                # (ops/cp.py): each page's L layer slots stay on one device
                raise ValueError(
                    f"num_pages={engine_config.num_pages} not divisible by "
                    f"the seq-parallel ring size {sp} (the page pool is "
                    f"context-sharded)"
                )
        # ring-attention dispatch reads this at trace time; ALWAYS set it
        # (including to None) so a previous engine's mesh never leaks into
        # this engine's traces
        set_active_mesh(mesh)

        if params is not None:
            self.params = params
        elif model_dir is not None:
            from llms_on_kubernetes_tpu.engine.weights import load_hf_params
            self.params = load_hf_params(
                cfg, model_dir, mesh=mesh, dtype=engine_config.dtype,
                quantization=engine_config.quantization,
                preload=weights_preload,
            )
        else:  # random weights (tests / benchmarks)
            self.params = init_params(cfg, jax.random.key(engine_config.seed),
                                      dtype=engine_config.dtype)
            if engine_config.quantization is not None:
                # random weights have no checkpoint format: every
                # quantization mode serves weight-only int8 (smoke tests)
                from llms_on_kubernetes_tpu.ops.quant import quantize_params
                self.params = quantize_params(self.params)
            if mesh is not None:
                from llms_on_kubernetes_tpu.parallel.sharding import shard_params
                self.params = shard_params(self.params, cfg, mesh)

        self.cache_config = CacheConfig(
            num_layers=cfg.num_layers,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            num_pages=engine_config.num_pages,
            page_size=engine_config.page_size,
            pages_per_slot=engine_config.pages_per_slot,
            dtype=engine_config.dtype,
            kv_dtype=engine_config.kv_cache_dtype,
        )
        self.k_pages, self.v_pages = init_pages(self.cache_config)
        if (engine_config.kv_cache_dtype == "int8"
                and engine_config.page_size % 128 != 0
                and jax.default_backend() == "tpu"):
            import logging
            logging.getLogger(__name__).warning(
                "kv_cache_dtype=int8 with page_size=%d: the Pallas int8 "
                "decode kernel needs a 128-multiple page size (Mosaic lane "
                "tiling); decode attention falls back to the slower XLA "
                "gather path", engine_config.page_size)
        if mesh is not None:
            from llms_on_kubernetes_tpu.parallel.sharding import shard_pool
            self.k_pages = shard_pool(self.k_pages, cfg, mesh)
            self.v_pages = shard_pool(self.v_pages, cfg, mesh)

        B = engine_config.max_decode_slots
        self.allocator = PageAllocator(
            engine_config.num_pages, engine_config.page_size, B,
            engine_config.pages_per_slot,
            prefix_caching=engine_config.prefix_caching,
        )
        self.slots: list[Optional[Request]] = [None] * B
        self.slot_len = np.zeros((B,), np.int64)  # tokens whose KV is cached
        # host-RAM offload tier: finished/preempted slots spill full pages
        # here; a returning session re-uploads them and skips straight to
        # decode (engine/cache.HostKVCache). Requires prefix caching — the
        # allocator's digest chain is the addressing scheme for both tiers.
        # (disabled under multihost: uploads mutate the pools outside the
        # broadcast protocol, so follower pods would silently diverge)
        self.host_kv: Optional[HostKVCache] = None
        if (engine_config.kv_host_cache_gb > 0
                and engine_config.prefix_caching
                and not engine_config.multihost):
            self.host_kv = HostKVCache(
                int(engine_config.kv_host_cache_gb * (1 << 30)),
                engine_config.page_size)
        # spills in flight: [(tenant, [digests], device gather)] — the
        # device->host copy is dispatched at free/preempt time but the
        # blocking np.asarray read happens at the next admission probe
        # (_drain_spills), keeping it off the decode hot path
        self._pending_spills: list = []
        # per-slot host-tier adoption staged between the admission probe
        # and its commit/rollback: (matched digests, payloads)
        self._host_adopt: dict = {}
        self.kv_upload_obs: "collections.deque[float]" = collections.deque(
            maxlen=4096)  # seconds per host->device page upload batch
        self.kv_uploaded_tokens = 0  # tokens whose re-prefill was skipped
        self._spill_gather = jax.jit(_spill_gather_pages)
        self._upload_scatter = jax.jit(_upload_scatter_pages,
                                       donate_argnums=(0, 1))
        # per-tenant fair admission (engine/qos.py): priority classes +
        # deficit round-robin keyed by Request.tenant; deque-compatible
        # for every scheduler call site (peek/popleft/appendleft/...)
        self.waiting: TenantFairQueue = TenantFairQueue(
            weights=dict(engine_config.qos_weights),
            default_weight=engine_config.qos_default_weight,
            starvation_s=engine_config.qos_starvation_s,
        )
        # per-tenant admission accounting, drained by the serving loop
        # into llm_tenant_* series: total admitted per (tenant, priority)
        # and (tenant, queue-wait seconds, priority) observations
        self.tenant_admitted: "collections.Counter" = collections.Counter()
        self.tenant_wait_obs: "collections.deque" = collections.deque(
            maxlen=4096)
        self._key = jax.random.key(engine_config.seed)
        self._id_counter = iter(range(2 ** 62))
        self._seed_rng = np.random.default_rng(engine_config.seed)
        self._lock = threading.Lock()
        self.preemptions = 0  # total KV-pressure preemptions (metrics)
        # fused multi-step decode accounting (metrics + bench):
        self.decode_dispatches = 0   # decode device dispatches
        self.decode_tokens = 0       # tokens committed to streams by decode
        self.early_exit_steps = 0    # planned row-steps wasted mid-window
        # speculative decoding accounting (metrics + bench): drafted /
        # accepted count DRAFT tokens only (the bonus token is ordinary
        # decode output), so accepted/drafted is the pure draft hit-rate
        self.spec_dispatches = 0
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        # per-dispatch consumed window depth; drained by the serving
        # loop into the llm_decode_steps_per_dispatch histogram
        self.steps_obs: "collections.deque[int]" = collections.deque(
            maxlen=4096)
        # seconds the ENGINE thread spent blocked on device reads (sync
        # path); async-path device waits land on the harvester thread's
        # own counter — device_wait_s() sums both for step attribution
        self._device_time_s = 0.0
        # goodput ledger: chip-time attribution + MFU/MBU + step-time
        # anomaly detection (engine/ledger.py); None = accounting off
        self.ledger = None
        if engine_config.ledger:
            import os

            from llms_on_kubernetes_tpu.engine.ledger import (
                GoodputLedger, StepAnomalyDetector,
            )

            det = None
            if engine_config.anomaly_profile:
                det = StepAnomalyDetector(
                    threshold=engine_config.anomaly_z,
                    cooldown_s=engine_config.anomaly_cooldown_s,
                    sustain=int(os.environ.get("LLMK_ANOMALY_SUSTAIN", "3")),
                    warmup=int(os.environ.get("LLMK_ANOMALY_WARMUP", "12")),
                )
            self.ledger = GoodputLedger(cfg, detector=det)
        # in-flight prefill dispatches awaiting their priority read:
        # key -> (launch time, [(request, prefill tokens), ...])
        self._ledger_prefills: dict[int, tuple[float, list]] = {}

        self._prefill_packed = jax.jit(
            _prefill_packed_step, static_argnums=(1,), donate_argnums=(4, 5, 6)
        )
        self._decode_packed = jax.jit(
            _decode_packed_step, static_argnums=(1,), donate_argnums=(5, 6, 7)
        )
        self._decode_multi = jax.jit(
            _decode_multi_packed_step, static_argnums=(1, 2),
            donate_argnums=(6, 7, 8)
        )
        self._decode_spec = jax.jit(
            _decode_spec_packed_step, static_argnums=(1, 2),
            donate_argnums=(4, 5, 6)
        )
        self._chunk_packed = jax.jit(
            _chunk_packed_step, static_argnums=(1,), donate_argnums=(4, 5, 6)
        )
        if cfg.vision is not None:
            from llms_on_kubernetes_tpu.models.vision import (
                encode_images, encode_images_qwen3vl, encode_video_qwen3vl,
            )

            self._mm_prefill_packed = jax.jit(
                _prefill_mm_packed_step, static_argnums=(1,),
                donate_argnums=(7, 8, 9))
            qwen = cfg.vision.family == "qwen3vl"
            enc = encode_images_qwen3vl if qwen else encode_images
            self._encode_images = jax.jit(enc, static_argnums=(1,))
            if qwen:
                self._encode_video = jax.jit(encode_video_qwen3vl,
                                             static_argnums=(1,))
        # per-slot OUTPUT-token counts for presence/frequency penalties;
        # donated through every step like the page pools
        self.token_counts = jnp.zeros((B, cfg.vocab_size), jnp.int32)

        # multi-host: every device call is announced in one packed broadcast
        # (engine/multihost.py). Async scheduling works across hosts — the
        # decode merge consumes device-resident tokens, so followers never
        # need host values.
        if engine_config.multihost:
            from llms_on_kubernetes_tpu.engine.multihost import ProtoShapes

            self._mh_shapes = ProtoShapes.from_engine_config(
                engine_config, self.model_config)

        # async scheduling state (see EngineConfig.async_scheduling)
        self._async = bool(engine_config.async_scheduling)
        self._inflight: "collections.deque[InflightStep]" = collections.deque()
        # (request, priority key, row) awaiting a first-token read
        self._pending_first: list[tuple[Request, int, int]] = []
        self._seq_counter = iter(range(2 ** 62))     # decode steps (dense)
        self._first_counter = iter(range(2 ** 62))   # priority prefill reads
        # set by submit(): breaks the backpressure wait so admission (and
        # the new request's prefill dispatch) never waits out a read
        self._admit_wake = threading.Event()
        self._harvester: Optional[_Harvester] = None
        if self._async:
            self._harvester = _Harvester()
            self._harvester.start()
            import weakref
            weakref.finalize(self, self._harvester.stop)
        # device-resident zero vectors for the packed steps (uploaded once)
        self._zeros_B = jnp.zeros((B,), jnp.int32)
        self._zeros_1 = jnp.zeros((1,), jnp.int32)
        # decode-row template cache (PR 3, profile-guided): the decode
        # packed array is mostly request-STATIC sampling columns, and
        # rebuilding every one of them per step in a Python loop (plus
        # _pack_bias over the whole bias block) was the top non-kernel
        # slice of the decode step. The static columns are written once
        # per slot OCCUPANCY into this template; each step memcpys it and
        # fills only the dynamic columns (_dec_template below).
        self._dec_rows = np.zeros(
            (B, _DEC_COLS + engine_config.pages_per_slot), np.int32)
        self._dec_rows[:, 1] = 1                               # src: host
        self._dec_rows[:, 5] = np.float32(1.0).view(np.int32)  # top_p off
        self._dec_rows[:, _ADP_DEC] = -1                       # base model
        self._dec_rows[:, _FSM_DEC] = -1                       # no grammar
        self._dec_rows[:, _STOP_DEC:_STOP_DEC + STOP_SLOTS] = -1  # no stops
        self._dec_row_owner: list = [None] * B
        # grammar-constrained decoding: resident-grammar registry + device
        # tables, created lazily on the first constrained admission
        # (engine/grammar.py; _ensure_grammar/_fsm_args below)
        self._g_resident: dict = {}      # key -> [row, base, size, refs, g]
        self._fsm_state = None           # device [B] int32
        self._g_class_h = None           # host [G, vocab] int16
        self._g_trans_h = None           # host [S_cap, C_cap] int16
        self._g_dev = None               # (class_of, trans) device arrays
        # pacing state: EMA of the device step time (measured from harvest
        # completion spacing — in steady state the loop is device-paced)
        # and the estimated wall time when all dispatched work completes
        self._est_step = 0.02
        self._busy_until = 0.0
        self._last_harvest_t: Optional[float] = None
        # watchdog: set by _shed_wedged() when a device step exceeded the
        # stall budget; a wedged engine rejects submissions (the server
        # flips readiness and a restart is the only recovery)
        self.wedged = False
        # prompt scoring (echo+logprobs): wrapper built eagerly — jit()
        # itself is free, compilation is per-shape on first use, and an
        # unsynchronized lazy init would let concurrent requests each pay
        # a duplicate compile through their own wrapper
        from llms_on_kubernetes_tpu.models.decoder import forward_score

        self._score_jit = jax.jit(forward_score, static_argnums=(1, 4))

        # multi-tenant LoRA: attach zeroed per-target LoRAStacks to the
        # params and build the slot manager. With no adapters configured
        # the stacks are never created and every trace above stays the
        # byte-identical pre-LoRA program.
        self._adapters = None
        if engine_config.adapters:
            self._init_adapters()

        # speculative decoding (engine/speculation.py): built only when
        # configured AND structurally possible — the async fused window is
        # the substrate (drafts ride _BUD_DEC rows), so sync scheduling or
        # K=1 (incl. the multihost clamp) leaves self._spec = None and the
        # engine byte-identical to the pre-speculation program
        self._spec = None
        if (engine_config.speculation is not None and self._async
                and engine_config.decode_steps > 1):
            from llms_on_kubernetes_tpu.engine.speculation import (
                build_speculator,
            )
            self._spec = build_speculator(engine_config, self.model_config)

    # ------------------------------------------------------------------
    # multi-tenant LoRA (engine/adapters.py, ops/lora.py)
    # ------------------------------------------------------------------

    @property
    def adapters(self):
        """The AdapterManager, or None on an adapter-free engine."""
        return self._adapters

    def _init_adapters(self) -> None:
        from llms_on_kubernetes_tpu.engine.adapters import (
            AdapterManager, load_adapter,
        )
        from llms_on_kubernetes_tpu.ops.lora import lora_zeros

        cfg = self.model_config
        ec = self.config
        if ec.multihost:
            # follower pods replay packed steps against their own params
            # copy and have no upload path for slot residency changes
            raise ValueError(
                "multi-tenant LoRA adapters are not supported with "
                "multihost=true")
        if cfg.num_experts and any(
                t.startswith("w_") for t in ec.adapter_targets):
            raise ValueError(
                f"adapter_targets {ec.adapter_targets} include MLP "
                f"projections, but {cfg.name!r} is MoE (per-expert LoRA "
                f"is not supported); use attention-only targets")
        D, F = cfg.hidden_size, cfg.intermediate_size
        H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        # (in_shape, out_shape) in the decoder's einsum layouts
        shapes = {
            "wq": ((D,), (H, hd)), "wk": ((D,), (KV, hd)),
            "wv": ((D,), (KV, hd)), "wo": ((H, hd), (D,)),
            "w_gate": ((D,), (F,)), "w_up": ((D,), (F,)),
            "w_down": ((F,), (D,)),
        }
        S, r = ec.adapter_slots, ec.adapter_rank
        for t in ec.adapter_targets:
            if t not in shapes:
                raise ValueError(f"unknown adapter target {t!r} "
                                 f"(supported: {sorted(shapes)})")
            stack = lora_zeros(cfg.num_layers, S, *shapes[t], r)
            if self.mesh is not None:
                from llms_on_kubernetes_tpu.parallel.sharding import (
                    shard_lora_stack,
                )
                stack = shard_lora_stack(stack, self.mesh)
            self.params["layers"]["lora_" + t] = stack

        def _load(name: str, ref: str):
            from llms_on_kubernetes_tpu.engine.hub import ensure_adapter_dir

            return load_adapter(name, ensure_adapter_dir(ref), cfg, r,
                                targets=ec.adapter_targets)

        self._adapters = AdapterManager(
            dict(ec.adapters), S, _load, self._upload_adapter)

    def _upload_adapter(self, slot: int, loaded) -> None:
        """Copy one adapter's factors into device slot ``slot`` of every
        target stack (zeroing targets it doesn't train). Safe while steps
        are in flight: params is a non-donated jit argument, so dispatched
        steps hold the previous buffers by value."""
        from llms_on_kubernetes_tpu.ops.lora import LoRAStack

        layers = self.params["layers"]
        for t in self.config.adapter_targets:
            stack = layers["lora_" + t]
            fac = loaded.factors.get(t)
            if fac is None:
                a = stack.a.at[:, slot].set(0.0)
                b = stack.b.at[:, slot].set(0.0)
            else:
                a = stack.a.at[:, slot].set(jnp.asarray(fac[0]))
                b = stack.b.at[:, slot].set(jnp.asarray(fac[1]))
            layers["lora_" + t] = LoRAStack(a, b, rank_axis=stack.rank_axis)

    def _ensure_adapter(self, req: "Request") -> bool:
        """Pin ``req``'s adapter into a device slot; False = every slot is
        pinned by running requests — the caller waits, like page pressure."""
        if req.adapter is None or req.adapter_slot >= 0:
            return True
        slot = self._adapters.acquire(req.adapter)
        if slot is None:
            return False
        req.adapter_slot = slot
        return True

    def _release_adapter(self, req: "Request") -> None:
        if req.adapter_slot >= 0 and self._adapters is not None:
            self._adapters.release(req.adapter_slot)
            req.adapter_slot = -1

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def submit(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        on_event=None,
        images=None,
        deadline: Optional[float] = None,
        adapter: Optional[str] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        handoff: bool = False,
    ) -> Request:
        if self.wedged:
            raise EngineStallError(
                "engine wedged: a device step stalled past the watchdog "
                "budget; restart the server to recover")
        params = params or SamplingParams()
        max_len = self.config.max_model_len
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if adapter is not None and (
                self._adapters is None or not self._adapters.known(adapter)):
            raise UnknownAdapterError(
                f"adapter {adapter!r} is not served by this engine "
                f"(configured: {self._adapters.names() if self._adapters else []})")
        if images is not None:
            # normalize to a LIST of float32 arrays — [H, W, C] = an
            # image, [F, H, W, C] = a VIDEO's frames (Qwen3-VL; F frames
            # in temporal-patch multiples). Dynamic resolution allows
            # per-entry grids, so one request may mix shapes and kinds.
            images = [np.asarray(im, np.float32) for im in images]
            params = self._validate_images(prompt, params, images)
        if params.top_k > MAX_CANDIDATES:
            raise ValueError(
                f"top_k={params.top_k} exceeds the sampling candidate pool "
                f"({MAX_CANDIDATES}); values above it are not supported"
            )
        if params.grammar is not None:
            g = params.grammar
            if (g.n_states > self.config.grammar_states
                    or g.n_classes > self.config.grammar_classes):
                raise ValueError(
                    f"grammar needs {g.n_states} states / {g.n_classes} "
                    f"token classes; this engine's device-table caps are "
                    f"{self.config.grammar_states} / "
                    f"{self.config.grammar_classes}")
            if len(g.class_of) > self.model_config.vocab_size:
                raise ValueError(
                    f"grammar was compiled for a {len(g.class_of)}-token "
                    f"vocabulary; the model's is "
                    f"{self.model_config.vocab_size}")
        for name in ("presence_penalty", "frequency_penalty"):
            val = getattr(params, name)
            if not -2.0 <= val <= 2.0:
                raise ValueError(f"{name} must be in [-2, 2], got {val}")
        if len(params.logit_bias) > LOGIT_BIAS_SLOTS:
            raise ValueError(
                f"logit_bias supports at most {LOGIT_BIAS_SLOTS} entries, "
                f"got {len(params.logit_bias)}")
        if len(params.stop_token_ids) > STOP_SLOTS:
            # the fused decode window's on-device early-exit mask carries
            # stop ids in STOP_SLOTS packed columns — a hard bound
            raise ValueError(
                f"stop_token_ids supports at most {STOP_SLOTS} entries, "
                f"got {len(params.stop_token_ids)}")
        seen_bias: set[int] = set()
        for tid, _bv in params.logit_bias:
            if not 0 <= tid < self.model_config.vocab_size:
                raise ValueError(
                    f"logit_bias token id {tid} outside the vocabulary "
                    f"(size {self.model_config.vocab_size})")
            if tid in seen_bias:
                # the on-device scatter-ADD would apply duplicates
                # cumulatively, silently diverging from the documented
                # map semantics (unreachable via the API — dict keys are
                # unique — but direct submit()s must not differ)
                raise ValueError(f"logit_bias has duplicate token id {tid}")
            seen_bias.add(tid)
        # prompts longer than the largest prefill bucket are served too:
        # admission splits them into bucket-size chunks against the paged
        # pool (chunked prefill — forward_chunk). The only hard limit is
        # the slot's page capacity below.
        # prompt + 1 sampled token must fit a slot's pages — a prompt that can
        # never be admitted would livelock the whole waiting queue behind it.
        if len(prompt) + 1 > max_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens cannot fit max_model_len="
                f"{max_len} (page_size*pages_per_slot) with room to generate"
            )
        if len(prompt) + params.max_tokens > max_len:
            params = dataclasses.replace(
                params, max_tokens=max(1, max_len - len(prompt))
            )
        prefix = list(params.prefix_tokens or ())
        if prefix:
            vocab = self.model_config.vocab_size
            for t in prefix:
                if not isinstance(t, int) or not 0 <= t < vocab:
                    raise ValueError(
                        f"prefix_tokens contains id {t!r} outside the "
                        f"vocabulary (size {vocab})")
            if len(prompt) + len(prefix) + 1 > max_len:
                raise ValueError(
                    f"prompt ({len(prompt)}) + prefix_tokens ({len(prefix)}) "
                    f"cannot fit max_model_len={max_len} with room to "
                    f"generate")
            if len(prefix) >= params.max_tokens:
                raise ValueError(
                    f"prefix_tokens ({len(prefix)}) already meets "
                    f"max_tokens ({params.max_tokens}); nothing left to "
                    f"generate")
        # mask to int32 range: the seed rides in int32 device arrays, and
        # an unchecked 64-bit client seed would OverflowError inside step()
        seed = (params.seed if params.seed is not None
                else int(self._seed_rng.integers(0, 2 ** 31 - 1))) & 0x7FFFFFFF
        # mrope delta is a pure function of the prompt: compute it ONCE at
        # submit so a cache-hit admission (which skips the mm prefill that
        # used to derive it) still decodes at the right rotary positions
        mrope_delta = 0
        if images is not None and self.model_config.mrope_section is not None:
            from llms_on_kubernetes_tpu.models.vision import qwen_mrope_positions

            _, mrope_delta = qwen_mrope_positions(
                list(prompt), self.model_config.image_token_id,
                self.model_config.vision.mm_tokens_per_image,
                grids=self._mm_grids(images))
        # QoS identity: the fair-queue bucket ("" = shared default) and the
        # priority class, resolved submit arg > per-tenant config > default
        tenant = str(tenant) if tenant else ""
        if priority is None:
            priority = dict(self.config.qos_priorities).get(
                tenant, self.config.qos_default_priority)
        priority = normalize_priority(
            priority, self.config.qos_default_priority)
        req = Request(
            id=request_id or f"req-{next(self._id_counter)}",
            prompt=list(prompt), params=params, seed=seed, images=images,
            mrope_delta=mrope_delta,
            cache_salt=self._cache_salt_for(images),
            deadline=deadline, adapter=adapter,
            tenant=tenant, priority=priority, handoff=handoff,
            # a non-empty output at submit makes admission take the
            # resumed re-prefill path (prompt + output), continuing the
            # stream exactly where the prefix left off; logprob data for
            # prefix tokens was generated elsewhere and is unrecoverable
            output=prefix, output_logprobs=[None] * len(prefix),
            on_event=on_event,  # attached BEFORE queueing: no missed events
        )
        with self._lock:
            if len(self.waiting) >= self.config.max_waiting:
                raise QueueFullError(
                    f"waiting queue is full ({self.config.max_waiting} "
                    f"requests); retry later"
                )
            self.waiting.append(req)
        if self._harvester is not None:
            self._admit_wake.set()
            self._harvester.poke()  # break any backpressure wait: admit NOW
        return req

    def _validate_images(self, prompt: list[int],
                         params: SamplingParams, images) -> SamplingParams:
        """Multimodal admission contract: the prompt's image-soft-token
        count must match the images, the whole prompt must fit one prefill
        bucket (the chunk path has no embedding substitution), and
        generation is capped so a preempted resume re-prefills in-bucket."""
        cfg = self.model_config
        if cfg.vision is None:
            raise ValueError(
                f"model {cfg.name!r} has no vision tower; images are not "
                f"supported")
        v = cfg.vision
        S2 = (v.image_size // v.patch_size) ** 2
        total_chunks = 0  # images count 1; videos F/temporal_patch_size
        for im in images:
            if im.ndim == 4:  # video frames [F, H, W, C]
                if v.family != "qwen3vl":
                    raise ValueError(
                        f"model {cfg.name!r} does not accept video input")
                F = im.shape[0]
                if F < v.temporal_patch_size or F % v.temporal_patch_size:
                    raise ValueError(
                        f"video frame count {F} must be a positive "
                        f"multiple of {v.temporal_patch_size}")
                frame, chunks = im[0], F // v.temporal_patch_size
            elif im.ndim == 3:
                frame, chunks = im, 1
            else:
                raise ValueError(
                    f"each entry must be an [H, W, C] image or an "
                    f"[F, H, W, C] video; got {tuple(im.shape)}")
            total_chunks += chunks
            if frame.shape[-1] != v.num_channels:
                raise ValueError(
                    f"images need {v.num_channels} channels; got "
                    f"{tuple(im.shape)}")
            sh = frame.shape[0] // v.patch_size
            sw = frame.shape[1] // v.patch_size
            if v.family == "qwen3vl":
                # dynamic resolution: any grid with the fixed patch budget
                # whose sides divide into merge blocks (the preprocessor
                # only produces these; validate so raw submit()s get 400s)
                m = v.spatial_merge_size
                if (sh * v.patch_size != frame.shape[0]
                        or sw * v.patch_size != frame.shape[1]
                        or sh % m or sw % m or sh * sw != S2):
                    raise ValueError(
                        f"image {frame.shape[0]}x{frame.shape[1]} is not an "
                        f"allowed dynamic-resolution grid ({S2} patches, "
                        f"sides divisible by {m * v.patch_size})")
            elif frame.shape[:2] != (v.image_size, v.image_size):
                raise ValueError(
                    f"{cfg.name} images must be {v.image_size}x"
                    f"{v.image_size}; got {frame.shape[0]}x{frame.shape[1]}")
        if total_chunks < 1 or total_chunks > self.config.max_images_per_request:
            raise ValueError(
                f"{total_chunks} image/frame blocks; this engine serves 1.."
                f"{self.config.max_images_per_request} per request (a video "
                f"counts one block per {v.temporal_patch_size} frames)")
        t_img = cfg.vision.mm_tokens_per_image
        soft = sum(1 for t in prompt if t == cfg.image_token_id)
        if soft != total_chunks * t_img:
            raise ValueError(
                f"prompt has {soft} image soft tokens; {total_chunks} "
                f"image/frame blocks need {total_chunks * t_img}")
        # soft tokens must form contiguous runs of exactly t_img (the
        # substitution/positions math assumes it; validating HERE keeps a
        # malformed prompt a 400, not an engine-thread exception later)
        i = 0
        while i < len(prompt):
            if prompt[i] == cfg.image_token_id:
                run = 0
                while (i < len(prompt)
                       and prompt[i] == cfg.image_token_id):
                    run += 1
                    i += 1
                if run != t_img:
                    raise ValueError(
                        f"image soft tokens must form runs of exactly "
                        f"{t_img}; found a run of {run}")
            else:
                i += 1
        bucket = max(self.config.prefill_buckets)
        if len(prompt) > bucket:
            raise ValueError(
                f"multimodal prompt of {len(prompt)} tokens exceeds the "
                f"largest prefill bucket ({bucket})")
        # keep prompt+output re-prefillable in one bucket after preemption
        if len(prompt) + params.max_tokens - 1 > bucket:
            params = dataclasses.replace(
                params, max_tokens=max(1, bucket - len(prompt) + 1))
        return params

    def has_work(self) -> bool:
        return (bool(self.waiting) or any(r is not None for r in self.slots)
                or bool(self._inflight) or bool(self._pending_first))

    # ------------------------------------------------------------------
    # scheduler iteration
    # ------------------------------------------------------------------

    def device_wait_s(self) -> float:
        """Cumulative seconds spent blocked on device work across the
        engine thread (sync reads) and the harvester (async reads). The
        serving loop differences this around each step() to attribute
        step wall time kernel-vs-host for the flight recorder."""
        total = self._device_time_s
        if self._harvester is not None:
            total += self._harvester.device_time_s
        return total

    def step(self) -> list[StepEvent]:
        # re-assert THIS engine's mesh for any trace this step triggers:
        # the active-mesh context is process-global, and constructing
        # another Engine (tests, rolling restarts) between our __init__
        # and our first trace would otherwise leak ITS mesh into OUR
        # executables (observed: a CP engine traced mesh-less)
        from llms_on_kubernetes_tpu.engine.cache import set_kv_write_strategy
        from llms_on_kubernetes_tpu.parallel.mesh import set_active_mesh

        set_active_mesh(self.mesh)
        set_kv_write_strategy(self.config.kv_write)
        events: list[StepEvent] = []
        if self.wedged:
            # nothing left to drive; reap anything that slipped in between
            # the wedge and the server's readiness flip
            events += self._reap_aborted()
            for ev in events:
                payload = (ev.new_tokens, ev.finished, ev.finish_reason)
                ev.request.events.put(payload)
                if ev.request.on_event is not None:
                    ev.request.on_event(payload)
            return events
        events += self._reap_aborted()
        if self._async:
            try:
                admitted = self._admit_async(events)
                status = self._launch_decode_async(admitted, events)
                events += self._harvest(drain=status == "idle")
            except EngineStallError as e:
                events += self._shed_wedged(str(e))
                status = "idle"
            if status == "paced" and not events and not self.waiting:
                # nothing to do until device work completes; a bounded nap
                # keeps the loop from burning the GIL the harvester needs
                # (admissions arriving mid-nap wait <= 1 ms)
                time.sleep(0.001)
        else:
            events += self._admit_one()
            events += self._decode_once()
        for ev in events:
            payload = (ev.new_tokens, ev.finished, ev.finish_reason)
            ev.request.events.put(payload)
            if ev.request.on_event is not None:
                ev.request.on_event(payload)
        return events

    def abort(self, req: Request, reason: str = "abort") -> None:
        """Request cancellation from any thread (client disconnect, server-side
        stop sequence). The engine thread releases the slot/pages at the start
        of its next step and emits a final finished event."""
        req.abort_reason = reason

    def _reap_aborted(self) -> list[StepEvent]:
        # Deadline sweep first: an expired deadline becomes an abort with
        # reason "timeout", so the shed rides the exact same reap path as
        # client disconnects. Waiting requests are shed here WITHOUT ever
        # being admitted (no prefill burned); slotted requests release
        # their slot/pages at the start of this step.
        now = time.monotonic()
        with self._lock:
            for r in self.waiting:
                if (r.deadline is not None and now >= r.deadline
                        and not r.abort_reason and not r.finished):
                    r.abort_reason = "timeout"
        for r in self.slots:
            if (r is not None and r.deadline is not None
                    and now >= r.deadline
                    and not r.abort_reason and not r.finished):
                r.abort_reason = "timeout"
        events: list[StepEvent] = []
        with self._lock:
            doomed_waiting = [r for r in self.waiting
                              if r.abort_reason and not r.finished]
            for r in doomed_waiting:
                self.waiting.remove(r)
        for r in doomed_waiting:
            events.append(self._finish(r, r.abort_reason))
        for r in list(self.slots):
            if r is not None and r.abort_reason and not r.finished:
                events.append(self._finish(r, r.abort_reason))
        return events

    def _mh_send(self, op: int, **fields) -> None:
        """Announce the next device call to follower pods (no-op single-host).
        One packed broadcast per call — see engine/multihost.py."""
        if not self.config.multihost:
            return
        from llms_on_kubernetes_tpu.engine import multihost as mh

        mh.send_message(self._mh_shapes, op, **fields)

    def stop_followers(self) -> None:
        """Tell follower pods to exit their mirror loops (engine shutdown)."""
        if not self.config.multihost:
            return
        from llms_on_kubernetes_tpu.engine import multihost as mh

        from llms_on_kubernetes_tpu.parallel.distributed import is_coordinator

        if is_coordinator():
            mh.send_message(self._mh_shapes, mh.MSG_SHUTDOWN)

    def _pack_prefill_row(self, packed: np.ndarray, row: int, req: Request,
                          n: int, slot: int) -> None:
        req.admitted_at = time.monotonic()
        packed[row, 0] = n
        packed[row, 1] = req.params.top_k
        packed[row, 2] = np.float32(req.params.temperature).view(np.int32)
        packed[row, 3] = np.float32(req.params.top_p).view(np.int32)
        packed[row, 4] = req.seed
        packed[row, 5] = np.float32(req.params.presence_penalty).view(np.int32)
        packed[row, 6] = np.float32(req.params.frequency_penalty).view(np.int32)
        packed[row, 7] = slot
        packed[row, 8] = len(req.prompt)  # output-token counting boundary
        packed[row, _ADP_PRE] = req.adapter_slot
        # fresh constrained rows start at the grammar's start state;
        # resumed rows (req.output non-empty) sample a DISCARDED token
        # unconstrained and their first decode fsm_sets the replayed state
        if req.fsm_row >= 0 and not req.output:
            packed[row, _FSM_PRE] = req.fsm_row
            packed[row, _FSM_PRE + 1] = req.fsm_start
        else:
            packed[row, _FSM_PRE:_FSM_PRE + 2] = -1
        _pack_bias(packed, row, _BIAS_PRE, req.params)
        packed[row, _PRE_COLS:] = self.allocator.page_tables[slot]

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"no prefill bucket fits {n} tokens")

    def _chunked_prefill(self, slot: int, req: Request,
                         prefill_tokens: list[int], start: int = 0):
        """Prefill a prompt in bucket-size chunks against the paged pool
        (prefill-with-history attention, forward_chunk), beginning at
        position ``start`` (> 0 when a cached prefix was adopted — those
        positions' KV is already in the slot's pages). The slot's pages
        for the WHOLE prompt are already allocated/adopted. Pure dispatch:
        each chunk chains on the previous through the donated page pool —
        no host read here, so the async pipeline stays full. Returns the
        FINAL chunk's (packed result, device tokens) pair (row 0 is the
        request's first generated token)."""
        from llms_on_kubernetes_tpu.engine.multihost import MSG_CHUNK

        n = len(prefill_tokens)
        step = max(self.config.prefill_buckets)
        pps = self.allocator.pages_per_slot
        pack = toks = None
        pos = start
        while pos < n:
            m = min(step, n - pos)
            bucket = self._bucket_for(m)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :m] = prefill_tokens[pos:pos + m]
            packed = np.zeros((1, _CHK_COLS + pps), np.int32)
            packed[0, 0] = m
            packed[0, 1] = pos
            packed[0, 2] = req.params.top_k
            packed[0, 3] = np.float32(req.params.temperature).view(np.int32)
            packed[0, 4] = np.float32(req.params.top_p).view(np.int32)
            packed[0, 5] = req.seed
            packed[0, 6] = np.float32(req.params.presence_penalty).view(np.int32)
            packed[0, 7] = np.float32(req.params.frequency_penalty).view(np.int32)
            packed[0, 8] = slot
            packed[0, 9] = len(req.prompt)
            packed[0, 10] = 1 if pos == start else 0  # first chunk: reset counts
            packed[0, 11] = req.mrope_delta
            packed[0, _ADP_CHK] = req.adapter_slot
            # only the FINAL chunk's sample is the request's first real
            # token; earlier chunks (and every chunk of a resumed
            # request) sample discarded tokens unconstrained
            final = pos + m >= n
            if final and req.fsm_row >= 0 and not req.output:
                packed[0, _FSM_CHK] = req.fsm_row
                packed[0, _FSM_CHK + 1] = req.fsm_start
            else:
                packed[0, _FSM_CHK:_FSM_CHK + 2] = -1
            use_fsm = packed[0, _FSM_CHK] >= 0
            _pack_bias(packed, 0, _BIAS_CHK, req.params)
            packed[0, _CHK_COLS:] = self.allocator.page_tables[slot]
            self._mh_send(MSG_CHUNK, pre_tokens=tokens, pre_packed=packed,
                          fsm_used=use_fsm)
            (pack, toks, self.k_pages, self.v_pages, self.token_counts,
             new_state) = self._chunk_packed(
                self.params, self.model_config, jnp.asarray(tokens),
                jnp.asarray(packed), self.k_pages, self.v_pages,
                self.token_counts, self._key,
                self._fsm_args() if use_fsm else None,
            )
            if new_state is not None:
                self._fsm_state = new_state
            pos += m
        self.slot_len[slot] = n
        return pack, toks

    def _cache_salt_for(self, images) -> Optional[bytes]:
        """Prefix-cache digest salt, computed ONCE at submit (a blocked
        admission retries every engine iteration — re-hashing megabytes of
        pixels there, under the lock, would stall the scheduler).

        Text requests use the empty salt. Multimodal prompts mix a hash
        of the IMAGE BYTES into the chain (soft tokens share one
        placeholder id, so token-only hashing would alias different
        images); a cache hit is then only usable when it covers the whole
        image region, because the remainder replays through the TEXT
        chunk path (enforced at admission). mrope (Qwen3-VL) prompts are
        cacheable too: the chunk path carries the request's position
        delta (packed col 11), computed at submit."""
        if images is None:
            return b""
        import hashlib

        h = hashlib.sha256()
        for im in images:  # shape first: same bytes at a different grid
            h.update(np.asarray(im.shape, np.int64).tobytes())
            h.update(im.tobytes())
        return h.digest()

    def _adopt_cached_prefix(self, slot: int, req: Request,
                             prefill_tokens: list[int]) -> int:
        """Adopt the longest usable cached prefix for an admission attempt
        (shared by the sync and async paths). A multimodal hit must cover
        every image token — the remainder prefills via forward_chunk,
        which has no embedding substitution — else it is rolled back.

        With the host tier on, the device hit is extended by walking the
        SAME digest chain through ``host_kv`` from where the device map
        stopped; the combined token count is returned so every caller's
        existing logic (can_allocate / commit_adopt / chunk ``start=hit``)
        is tier-agnostic. The probe is pure peek — payload references are
        staged in ``_host_adopt[slot]`` and only ``_host_kv_commit`` (at
        admission commit) uploads pages and touches stats/recency, since
        a blocked admission re-probes every engine iteration."""
        if req.cache_salt is None:
            return 0
        hit = self.allocator.adopt_prefix(
            slot, prefill_tokens[:len(req.prompt)], salt=req.cache_salt)
        combined = hit
        if self.host_kv is not None:
            self._drain_spills()
            page = self.allocator.page_size
            # the host chain runs over the FULL prefill stream (prompt +
            # replayed output for a resume) — spills digest generated
            # tokens too, so a returning session skips those as well.
            # Cap leaves >= 1 token to prefill (its logits seed sampling).
            cap_pages = (len(prefill_tokens) - 1) // page
            start = hit // page
            if cap_pages > start:
                digests = self.allocator._digests(
                    prefill_tokens[:cap_pages * page], salt=req.cache_salt)
                matched, payloads = self.host_kv.match_chain(
                    req.tenant, digests, start)
                # handoff-ingested payloads crossed a network: a corrupt
                # or truncated page is treated as missing (chain stops,
                # remainder re-prefills) rather than crashing the upload
                from llms_on_kubernetes_tpu.engine.cache import \
                    payload_shape_ok
                for i, pl in enumerate(payloads):
                    if not payload_shape_ok(pl, self.cache_config):
                        matched, payloads = matched[:i], payloads[:i]
                        break
                combined = hit + len(matched) * page
                self._host_adopt[slot] = (start, matched, payloads)
        if combined and req.images is not None:
            last_img = max(i for i, t in enumerate(req.prompt)
                           if t == self.model_config.image_token_id)
            if combined <= last_img:
                if hit:
                    self.allocator.rollback_adopt(slot)
                self._host_adopt.pop(slot, None)
                return 0
        return combined

    def _drain_spills(self) -> None:
        """Land pending device->host page copies in the host tier. The
        gathers were DISPATCHED at free/preempt time (device program order
        makes the bytes exactly the committed KV); the blocking host read
        happens here, off the decode hot path."""
        if not self._pending_spills:
            return
        for tenant, digests, (k, v, ks, vs) in self._pending_spills:
            k = np.asarray(jax.device_get(k))
            v = np.asarray(jax.device_get(v))
            ks = None if ks is None else np.asarray(jax.device_get(ks))
            vs = None if vs is None else np.asarray(jax.device_get(vs))
            for j, d in enumerate(digests):
                self.host_kv.put(tenant, d, {
                    "k": k[:, j].copy(), "v": v[:, j].copy(),
                    "ks": None if ks is None else ks[:, j].copy(),
                    "vs": None if vs is None else vs[:, j].copy(),
                })
        self._pending_spills.clear()

    def _spill_slot(self, req: Request) -> None:
        """Queue a finishing/preempted slot's full pages for the host
        tier. Must run BEFORE ``allocator.free`` reuses the pages: the
        gather is dispatched now (device order => it reads this slot's
        committed writes, not a successor's), only the host copy is
        deferred to :meth:`_drain_spills`."""
        if (self.host_kv is None or req.cache_salt is None
                or req.slot < 0):
            return
        slot = req.slot
        page = self.allocator.page_size
        tokens = req.prompt + req.output
        n_full = min(len(tokens), int(self.slot_len[slot])) // page
        if n_full <= 0:
            return
        digests = self.allocator._digests(tokens[:n_full * page],
                                          salt=req.cache_salt)
        pages = self.allocator.slot_pages[slot][:n_full]
        keep = [(d, p) for d, p in zip(digests, pages) if p != 0]
        if not keep:  # page 0 is the never-read trash page: never spilled
            return
        L = self.cache_config.num_layers
        P = self.cache_config.num_pages
        flat = np.asarray([[l * P + p for l in range(L)]
                           for _, p in keep], np.int32)
        gathered = self._spill_gather(self.k_pages, self.v_pages,
                                      jnp.asarray(flat))
        self._pending_spills.append(
            (req.tenant, [d for d, _ in keep], gathered))
        if len(self._pending_spills) > 32:
            self._drain_spills()

    def _host_kv_commit(self, slot: int, req: Request) -> None:
        """An admission with a staged host-tier match landed: upload the
        matched pages into the slot's freshly allocated device pages
        (before the chunk prefill that reads them is dispatched), count
        hits/misses, refresh recency. No-op without a staged probe."""
        staged = self._host_adopt.pop(slot, None)
        if staged is None or self.host_kv is None:
            return
        dev_pages, matched, payloads = staged
        self.host_kv.commit(req.tenant, matched)
        if not payloads:
            return
        t0 = time.perf_counter()
        m = len(payloads)
        pages = self.allocator.slot_pages[slot][dev_pages:dev_pages + m]
        L = self.cache_config.num_layers
        P = self.cache_config.num_pages
        flat = np.asarray([[l * P + p for l in range(L)] for p in pages],
                          np.int32)
        k = np.stack([pl["k"] for pl in payloads], axis=1)
        v = np.stack([pl["v"] for pl in payloads], axis=1)
        quant = payloads[0]["ks"] is not None
        ks = (np.stack([pl["ks"] for pl in payloads], axis=1)
              if quant else None)
        vs = (np.stack([pl["vs"] for pl in payloads], axis=1)
              if quant else None)
        self.k_pages, self.v_pages = self._upload_scatter(
            self.k_pages, self.v_pages, jnp.asarray(flat),
            k, v, ks, vs)
        self.kv_upload_obs.append(time.perf_counter() - t0)
        self.kv_uploaded_tokens += m * self.allocator.page_size

    # ------------------------------------------------------------------
    # disaggregated prefill/decode handoff (openai_api drives these from
    # server threads; HostKVCache is internally locked for exactly this)
    # ------------------------------------------------------------------

    def handoff_digests(self, tokens, salt: bytes = b"") -> "list[bytes]":
        """Chained digests of the FULL pages of ``tokens`` — the handoff
        ticket's addressing of what :meth:`_finish` spilled. Pure hashing
        (no allocator state), safe from any thread."""
        page = self.allocator.page_size
        n_full = len(tokens) // page
        if n_full <= 0:
            return []
        return self.allocator._digests(tokens[:n_full * page], salt=salt)

    def prefix_filter_digests(self) -> "list[bytes]":
        """Every chained page digest currently addressable as cache on
        this replica — device prefix cache plus host tier — feeding the
        /ready bloom-filter advertisement the routers use for cache-aware
        placement. Snapshot semantics, safe from server threads."""
        out = self.allocator.prefix_digests()
        if self.host_kv is not None:
            seen = set(out)
            out.extend(d for d in self.host_kv.digests() if d not in seen)
        return out

    def host_kv_export(self, tenant: str, digests: "list[bytes]") \
            -> "list[Optional[dict]]":
        """Host-tier payloads for a pulling decode replica (None per
        missing page). Empty when the tier is off."""
        if self.host_kv is None:
            return [None] * len(digests)
        return self.host_kv.export(tenant, digests)

    def host_kv_ingest(self, tenant: str, digest: bytes,
                       payload: dict) -> bool:
        """Land one pulled handoff page in the LOCAL host tier so the
        next admission's ``_adopt_cached_prefix`` chain walk finds it.
        Shape/dtype-validated against this engine's pools; a payload that
        does not match is refused (False) and the admission re-prefills
        that page instead — degraded, never wrong bytes."""
        from llms_on_kubernetes_tpu.engine.cache import payload_shape_ok

        if self.host_kv is None or not payload_shape_ok(
                payload, self.cache_config):
            return False
        self.host_kv.put(tenant, digest, payload)
        return True

    def _mm_grids(self, images) -> list[tuple[int, int]]:
        """Per-BLOCK merged grids (rows, cols) in prompt-run order: one
        per image, one per video temporal patch (all a video's blocks
        share its grid)."""
        v = self.model_config.vision
        d = v.patch_size * v.spatial_merge_size
        out = []
        for im in images:
            if im.ndim == 4:
                g = (im.shape[1] // d, im.shape[2] // d)
                out += [g] * (im.shape[0] // v.temporal_patch_size)
            else:
                out.append((im.shape[0] // d, im.shape[1] // d))
        return out

    def _encode_request_images(self, images):
        """Encode each entry through the vision tower (one jitted call per
        entry shape — dynamic resolution means per-entry pixel shapes,
        each compiling once). Video entries yield one embed block per
        temporal patch. Returns (embeds [n_blocks, t_img, D],
        deepstack [n_taps, n_blocks, t_img, D] | None)."""
        cfg = self.model_config
        qwen = cfg.vision.family == "qwen3vl"
        embeds_l, deep_l = [], []
        for im in images:
            if im.ndim == 4:  # video: [T', t_img, D] blocks in one call
                e, d = self._encode_video(self.params["vision"], cfg.vision,
                                          jnp.asarray(im))
                embeds_l += list(e)
                if d is not None:
                    deep_l += [d[:, t] for t in range(d.shape[1])]
                continue
            out = self._encode_images(self.params["vision"], cfg.vision,
                                      jnp.asarray(im)[None])
            if qwen:
                e, d = out
                deep_l.append(None if d is None else d[:, 0])
            else:
                e = out
            embeds_l.append(e[0])
        embeds = jnp.stack(embeds_l)
        deep = (jnp.stack(deep_l, axis=1)
                if qwen and deep_l and deep_l[0] is not None else None)
        return embeds, deep

    def _mm_execute(self, images, tokens: np.ndarray, packed: np.ndarray,
                    pos3: Optional[np.ndarray]):
        """Vision encode + multimodal prefill for one admission — runs
        IDENTICALLY on the coordinator and on follower pods (both enter
        the same jitted programs in the same order; followers get the
        inputs by broadcast). Updates the pools/counts and returns the
        device SampleResult."""
        from llms_on_kubernetes_tpu.engine.cache import set_kv_write_strategy
        from llms_on_kubernetes_tpu.parallel.mesh import set_active_mesh

        set_active_mesh(self.mesh)  # follower_loop calls this directly
        set_kv_write_strategy(self.config.kv_write)
        cfg = self.model_config
        embeds, deep = self._encode_request_images(images)
        n_max = self.config.max_images_per_request
        if embeds.shape[0] < n_max:  # pad image count to the compiled shape
            pad = jnp.zeros((n_max - embeds.shape[0],) + embeds.shape[1:],
                            embeds.dtype)
            embeds = jnp.concatenate([embeds, pad])
            if deep is not None:
                dpad = jnp.zeros(deep.shape[:1] + (n_max - deep.shape[1],)
                                 + deep.shape[2:], deep.dtype)
                deep = jnp.concatenate([deep, dpad], axis=1)
        if deep is not None:  # configs without deepstack taps: None
            # flatten per row: [n_taps, 1(row), n_img_max*t_img, D]
            deep = deep.reshape(deep.shape[0], -1, deep.shape[-1])[:, None]
        pos3_dev = None if pos3 is None else jnp.asarray(pos3)
        use_fsm = bool(packed[0, _FSM_PRE] >= 0)  # same bytes on followers
        (pack, toks, self.k_pages, self.v_pages, self.token_counts,
         new_state) = self._mm_prefill_packed(
            self.params, cfg, jnp.asarray(tokens), jnp.asarray(packed),
            embeds[None], deep, pos3_dev, self.k_pages, self.v_pages,
            self.token_counts, self._key,
            self._fsm_args() if use_fsm else None,
        )
        if new_state is not None:
            self._fsm_state = new_state
        return pack, toks

    def _dispatch_mm_prefill(self, slot: int, req: Request,
                             prefill_tokens: list[int]):
        """Build a multimodal admission's inputs, announce them to
        follower pods (control word + pixel payload), and run the encode
        + prefill. Returns the device SampleResult."""
        cfg = self.model_config
        n = len(prefill_tokens)
        bucket = self._bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prefill_tokens
        packed = np.zeros((1, _PRE_COLS + self.allocator.pages_per_slot),
                          np.int32)
        self._pack_prefill_row(packed, 0, req, n, slot)
        pos3 = None
        if cfg.vision.family == "qwen3vl":
            from llms_on_kubernetes_tpu.models.vision import qwen_mrope_positions

            # delta is NOT re-assigned here: submit() already derived it
            # (the authoritative value — cache-hit admissions skip this
            # dispatch entirely and still need it for decode)
            p3, _delta = qwen_mrope_positions(
                prefill_tokens, cfg.image_token_id,
                cfg.vision.mm_tokens_per_image,
                prompt_len=len(req.prompt),
                grids=self._mm_grids(req.images))
            pos3 = np.zeros((1, 3, bucket), np.int32)
            pos3[0, :, :n] = p3
        if self.config.multihost:
            from llms_on_kubernetes_tpu.engine import multihost as mh

            self._mh_send(mh.MSG_MM_PREFILL, pre_tokens=tokens,
                          pre_packed=packed)
            mh.send_mm_payload(self._mh_shapes, req.images,
                               None if pos3 is None else pos3[0])
        pack, toks = self._mm_execute(req.images, tokens, packed, pos3)
        self.slot_len[slot] = n
        return pack, toks

    # ------------------------------------------------------------------
    # grammar-constrained decoding: device-table residency
    # ------------------------------------------------------------------

    def _fsm_args(self):
        """The (state, class_of, trans) device tuple, or None before the
        first constrained admission."""
        if self._fsm_state is None:
            return None
        return (self._fsm_state, *self._g_dev)

    def _fsm_any_active(self) -> bool:
        return any(r is not None and r.fsm_row >= 0 for r in self.slots)

    def _g_first_fit(self, size: int) -> Optional[int]:
        occ = sorted((e[1], e[1] + e[2]) for e in self._g_resident.values())
        base = 0
        for lo, hi in occ:
            if lo - base >= size:
                return base
            base = max(base, hi)
        return base if self.config.grammar_states - base >= size else None

    def _ensure_grammar(self, req: Request) -> bool:
        """Make the request's grammar resident in the device tables and
        point req.fsm_row/fsm_start at it (refcounted). Returns False when
        every table row / state range is pinned by RUNNING requests — the
        admission waits, exactly like page-pool pressure. Idempotent per
        request (a blocked admission retries every iteration)."""
        if req.fsm_row >= 0:
            return True
        g = req.params.grammar
        ent = self._g_resident.get(g.key)
        if ent is None:
            cfg = self.config
            if self._g_class_h is None:
                V = self.model_config.vocab_size
                self._g_class_h = np.zeros((cfg.max_grammars, V), np.int16)
                self._g_trans_h = np.full(
                    (cfg.grammar_states, cfg.grammar_classes), -1, np.int16)
                self._fsm_state = jnp.full(
                    (cfg.max_decode_slots,), -1, jnp.int32)
            while True:
                used = {e[0] for e in self._g_resident.values()}
                row = next((r for r in range(cfg.max_grammars)
                            if r not in used), None)
                base = self._g_first_fit(g.n_states)
                if row is not None and base is not None:
                    break
                victim = next((k for k, e in self._g_resident.items()
                               if e[3] == 0), None)
                if victim is None:
                    return False  # all pinned by running requests; wait
                del self._g_resident[victim]
            V = self.model_config.vocab_size
            co = np.full((V,), g.n_classes - 2, np.int16)  # pad: reject
            co[:len(g.class_of)] = g.class_of
            self._g_class_h[row] = co
            tr = g.trans.astype(np.int32)
            self._g_trans_h[base:base + g.n_states, :] = -1
            self._g_trans_h[base:base + g.n_states, :g.n_classes] = np.where(
                tr >= 0, tr + base, -1).astype(np.int16)
            ent = [row, base, g.n_states, 0, g]
            self._g_resident[g.key] = ent
            self._upload_grammars()
        ent[3] += 1
        req.fsm_row = ent[0]
        req.fsm_start = ent[1] + g.start
        return True

    def _upload_grammars(self) -> None:
        self._g_dev = (jnp.asarray(self._g_class_h),
                       jnp.asarray(self._g_trans_h))
        if self.config.multihost:
            from llms_on_kubernetes_tpu.engine import multihost as mh

            self._mh_send(mh.MSG_GRAMMAR)
            mh.send_grammar_payload(self._mh_shapes, self._g_class_h,
                                    self._g_trans_h)

    def _g_release(self, req: Request) -> None:
        """Drop the request's hold on its resident grammar (finish, abort,
        preemption — a resumed admission re-ensures residency)."""
        if req.fsm_row < 0 or req.params.grammar is None:
            return
        ent = self._g_resident.get(req.params.grammar.key)
        if ent is not None and ent[3] > 0:
            ent[3] -= 1
        req.fsm_row = -1
        req.fsm_start = -1
        req.pending_fsm_state = None

    def _fsm_replay(self, req: Request) -> None:
        """Resume-after-preemption: recompute the FSM state after every
        emitted token on the HOST (the device state was lost with the
        slot) and stage it for the next decode launch's fsm_set."""
        g = req.params.grammar
        s = g.start
        for t in req.output:
            s = g.next_state(s, t)
            if s < 0:
                import logging
                logging.getLogger(__name__).warning(
                    "request %s: emitted token %d not reachable in its own "
                    "grammar; continuing unconstrained", req.id, t)
                self._g_release(req)
                return
        req.pending_fsm_state = (req.fsm_start
                                 - g.start) + s  # base + replayed state

    def _admit_one(self) -> list[StepEvent]:
        """Admit + prefill at most one waiting request per iteration.

        A resumed (previously preempted) request re-prefills its prompt plus
        every already-emitted token except the pending one; the prefill's
        sampled token is discarded and the old pending token is restored, so
        the output stream is unaffected by preemption.
        """
        from llms_on_kubernetes_tpu import faults

        if faults.is_active("queue_stall"):
            # LLMK_FAULT=queue_stall: admission refuses while the flag is
            # set — waiting requests age in the queue (deadline-shed and
            # Retry-After paths become deterministically testable)
            return []
        with self._lock:
            if not self.waiting:
                return []
            slot = self._free_slot()
            if slot is None:
                return []
            req = self.waiting[0]
            if (req.params.grammar is not None
                    and not self._ensure_grammar(req)):
                return []  # all grammar rows pinned; wait like page pressure
            if req.adapter is not None and not self._ensure_adapter(req):
                return []  # every adapter slot pinned; wait like pages
            resumed = bool(req.output)
            prefill_tokens = req.prompt + (req.output[:-1] if resumed else [])
            n = len(prefill_tokens)
            if self.allocator.pages_needed(n + 1) > self.allocator.pages_per_slot:
                # resumed request grew beyond page reach; end it
                # gracefully rather than livelocking the queue behind it
                self.waiting.popleft()
                ev = self._finish(req, "length")
                return [ev]
            # adopt any cached prefix FIRST so can_allocate counts only the
            # private pages still needed; roll back if they don't fit yet
            hit = self._adopt_cached_prefix(slot, req, prefill_tokens)
            if not self.allocator.can_allocate(slot, n + 1):
                if hit:
                    self.allocator.rollback_adopt(slot)
                self._host_adopt.pop(slot, None)
                return []  # wait for pages to free up
            self.waiting.popleft()
        self.allocator.allocate(slot, n + 1)
        if hit:
            self.allocator.commit_adopt(slot, hit)
        self._note_admission(req)
        # host-tier pages upload BEFORE the prefill below is dispatched,
        # so its history attention reads the restored KV
        self._host_kv_commit(slot, req)
        self.slots[slot] = req
        req.slot = slot
        if resumed and req.fsm_row >= 0:
            self._fsm_replay(req)  # stages fsm_set for the next decode

        t_launch = time.monotonic()
        if req.images is not None and hit == 0:
            pack, _toks = self._dispatch_mm_prefill(slot, req, prefill_tokens)
        elif hit > 0 or n > max(self.config.prefill_buckets):
            # cache-hit admissions run the chunk path: prefill-with-history
            # attention over the remainder, history = the adopted prefix
            # (for a multimodal hit the remainder is pure text)
            pack, _toks = self._chunked_prefill(slot, req, prefill_tokens,
                                                start=hit)
        else:
            from llms_on_kubernetes_tpu.engine.multihost import MSG_PREFILL

            bucket = self._bucket_for(n)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :n] = prefill_tokens
            packed = np.zeros((1, _PRE_COLS + self.allocator.pages_per_slot),
                              np.int32)
            packed[:, _ADP_PRE] = -1
            packed[:, _FSM_PRE:_FSM_PRE + 2] = -1
            self._pack_prefill_row(packed, 0, req, n, slot)
            use_fsm = packed[0, _FSM_PRE] >= 0
            self._mh_send(MSG_PREFILL, pre_tokens=tokens, pre_packed=packed,
                          fsm_used=use_fsm)
            (pack, _toks, self.k_pages, self.v_pages, self.token_counts,
             new_state) = self._prefill_packed(
                self.params, self.model_config, jnp.asarray(tokens),
                jnp.asarray(packed), self.k_pages, self.v_pages,
                self.token_counts, self._key,
                self._fsm_args() if use_fsm else None,
            )
            if new_state is not None:
                self._fsm_state = new_state
            self.slot_len[slot] = n
        # the dispatched prefill writes these pages; device order makes
        # them valid for any later-dispatched adopter
        if req.cache_salt is not None:
            self.allocator.register_prefix(slot, req.prompt,
                                           salt=req.cache_salt)
        if resumed:
            req.pending_token = req.output[-1]
            return []
        t0 = time.perf_counter()
        host = HostSample(np.asarray(jax.device_get(pack)))
        self._device_time_s += time.perf_counter() - t0
        if self.ledger is not None:
            self.ledger.record(t_launch, time.monotonic(),
                               [(req, "prefill", n - hit or n)])
        first = int(host.tokens[0])
        req.pending_token = first
        req.first_token_at = time.monotonic()
        return self._emit(req, first, _lp_entry(host, 0))

    def _emit(self, req: Request, token: int,
              lp: Optional[tuple] = None) -> list[StepEvent]:
        """Record a sampled token (+ its logprob data) and decide whether
        the request finishes."""
        req.output.append(token)
        req.output_logprobs.append(lp)
        reason = None
        if token in set(req.params.stop_token_ids):
            reason = "stop"
        elif len(req.output) >= req.params.max_tokens:
            reason = "length"
        elif self.slot_len[req.slot] + 1 >= self.config.max_model_len:
            reason = "length"
        if reason is not None:
            self._finish(req, reason)
        return [StepEvent(req, [token], req.finished, reason)]

    def _finish(self, req: Request, reason: str) -> StepEvent:
        """Release a request's slot/pages and mark it finished."""
        req.finished = True
        req.finish_reason = reason
        req.finished_at = time.monotonic()
        if req.trace is not None:
            req.trace.event("finish", request=req.id, reason=reason,
                            tokens=len(req.output))
        self._g_release(req)
        self._release_adapter(req)
        if req.slot >= 0:
            # spill full pages to the host tier BEFORE the allocator can
            # hand them to another sequence (skip a wedged device: the
            # gather would never complete)
            if reason != "stalled" and not self.wedged:
                self._spill_slot(req)
                # a prefill-role replica's whole product is the spilled
                # pages: land them in the host tier BEFORE the finish
                # event reaches the server, so the handoff ticket never
                # races the decode replica's pull against a lazy drain
                if ((self.config.role == "prefill" or req.handoff)
                        and self.host_kv is not None):
                    self._drain_spills()
            self.allocator.free(req.slot)
            self.slot_len[req.slot] = 0
            self.slots[req.slot] = None
            req.slot = -1
        return StepEvent(req, [], True, reason)

    # ------------------------------------------------------------------
    # watchdog
    # ------------------------------------------------------------------

    def _stall_budget(self) -> Optional[float]:
        """Max seconds a blocking harvest wait may sit with no completion.

        ``max(watchdog_stall_s, 50 x recent step estimate)`` — the floor
        keeps a slow-but-live device (long prefill compile, first-step
        tracing) out of the abort path, while the step-estimate multiple
        scales up for genuinely slow configs. None disables the watchdog.
        """
        limit = self.config.watchdog_stall_s
        if not limit or limit <= 0:
            return None
        return max(float(limit), 50.0 * self._est_step)

    def _shed_wedged(self, why: str) -> list[StepEvent]:
        """A device step blew the watchdog budget: the accelerator (or its
        transport) is wedged and no in-flight work will ever complete.
        Finish every request with reason "stalled" so clients get a clean
        terminal event instead of a hang, and mark the engine wedged —
        submit() rejects from here on and the server flips readiness; a
        process restart is the only recovery."""
        from llms_on_kubernetes_tpu.server.tracing import jlog

        jlog("engine_wedged", why=why, waiting=len(self.waiting),
             active=sum(r is not None for r in self.slots))
        self.wedged = True
        events: list[StepEvent] = []
        with self._lock:
            doomed = list(self.waiting)
            self.waiting.clear()
        for r in doomed:
            if not r.finished:
                events.append(self._finish(r, "stalled"))
        for r in list(self.slots):
            if r is not None and not r.finished:
                events.append(self._finish(r, "stalled"))
        for req, _key, _row in self._pending_first:
            if not req.finished:
                events.append(self._finish(req, "stalled"))
        self._inflight.clear()
        self._pending_first = []
        self._ledger_prefills.clear()
        return events

    def _note_admission(self, req: Request) -> None:
        """Per-tenant admission accounting, recorded where a request takes
        its slot. Only FIRST admissions count (admitted_at is still None;
        a preemption round trip is not new tenant throughput) — the
        serving loop drains these into the llm_tenant_* series."""
        if req.admitted_at is not None:
            return
        self.tenant_admitted[(req.tenant, req.priority)] += 1
        self.tenant_wait_obs.append(
            (req.tenant, time.monotonic() - req.submitted_at, req.priority))

    def _preempt_youngest(self) -> None:
        """Free a victim's pages; requeue it to re-prefill (prompt +
        generated so far) when memory frees up. Victim selection is
        priority-aware: the lowest class sheds first (batch before normal
        before interactive), youngest submission breaking ties — KV
        pressure lands on the traffic the operator marked preemptible
        before it ever touches interactive streams."""
        victims = [r for r in self.slots if r is not None]
        if not victims:
            raise MemoryError("KV pool exhausted with no preemptable request")
        victim = max(victims,
                     key=lambda r: (priority_rank(r.priority), r.submitted_at))
        self.preemptions += 1
        if victim.trace is not None:
            victim.trace.event("preempted", request=victim.id,
                               tokens=len(victim.output))
        slot = victim.slot
        # park the victim's KV in the host tier: its re-admission resumes
        # from uploaded pages instead of re-prefilling from scratch
        self._spill_slot(victim)
        self.allocator.free(slot)
        self.slot_len[slot] = 0
        self.slots[slot] = None
        victim.slot = -1
        victim.pending_token = -1
        # release the grammar hold too: re-admission re-ensures residency
        # and host-replays the FSM state from the emitted tokens
        self._g_release(victim)
        # ... and the adapter pin: re-admission re-acquires (the factors
        # stay host-cached, so a round trip is an upload at worst)
        self._release_adapter(victim)
        with self._lock:
            self.waiting.appendleft(victim)

    def _dec_template(self, active) -> np.ndarray:
        """Fresh copy of the decode packed array with every request-STATIC
        column (sampling params, seed, mrope delta, grammar row, logit
        bias block) filled from the per-slot template cache. A slot's
        template rebuilds only when its occupant — or that occupant's
        grammar row — changes; vacated slots reset to the idle defaults.
        Callers write only the per-step dynamic columns (length, token
        source/value, prefill row, fsm force, page tables)."""
        tmpl = self._dec_rows
        owners = self._dec_row_owner
        occupant = dict(active)
        for i in range(self.config.max_decode_slots):
            r = occupant.get(i)
            if r is None:
                if owners[i] is not None:   # vacated: back to idle defaults
                    tmpl[i, :] = 0
                    tmpl[i, 1] = 1
                    tmpl[i, 5] = np.float32(1.0).view(np.int32)
                    tmpl[i, _ADP_DEC] = -1
                    tmpl[i, _FSM_DEC] = -1
                    tmpl[i, _STOP_DEC:_STOP_DEC + STOP_SLOTS] = -1
                    owners[i] = None
                continue
            fsm_row = r.fsm_row if r.fsm_row >= 0 else -1
            if (owners[i] is r and tmpl[i, _FSM_DEC] == fsm_row
                    and tmpl[i, _ADP_DEC] == r.adapter_slot):
                continue
            tmpl[i, :] = 0
            tmpl[i, 1] = 1
            tmpl[i, 3] = r.params.top_k
            tmpl[i, 4] = np.float32(r.params.temperature).view(np.int32)
            tmpl[i, 5] = np.float32(r.params.top_p).view(np.int32)
            tmpl[i, 6] = r.seed
            tmpl[i, 8] = np.float32(r.params.presence_penalty).view(np.int32)
            tmpl[i, 9] = np.float32(r.params.frequency_penalty).view(np.int32)
            tmpl[i, 10] = r.mrope_delta
            tmpl[i, _ADP_DEC] = r.adapter_slot
            tmpl[i, _FSM_DEC] = fsm_row
            tmpl[i, _STOP_DEC:_STOP_DEC + STOP_SLOTS] = -1
            for j, sid in enumerate(r.params.stop_token_ids):
                tmpl[i, _STOP_DEC + j] = sid
            _pack_bias(tmpl, i, _BIAS_DEC, r.params)
            owners[i] = r
        packed = tmpl.copy()
        packed[:, _DEC_COLS:] = self.allocator.page_tables
        return packed

    def _decode_once(self) -> list[StepEvent]:
        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return []

        # grow page tables; preempt on exhaustion
        for i, r in list(active):
            while True:
                if self.slots[i] is not r:
                    # r was preempted by an earlier iteration's MemoryError;
                    # allocating would leak a page into the vacated slot
                    break
                try:
                    self.allocator.allocate(i, int(self.slot_len[i]) + 1)
                    break
                except MemoryError:
                    self._preempt_youngest()
        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return []

        from llms_on_kubernetes_tpu.engine.multihost import MSG_DECODE

        self.decode_dispatches += 1
        self.decode_tokens += len(active)
        self.steps_obs.append(1)
        t_launch = time.monotonic()
        packed = self._dec_template(active)
        for i, r in active:
            packed[i, 0] = self.slot_len[i] + 1
            packed[i, 2] = r.pending_token
            if r.fsm_row >= 0 and r.pending_fsm_state is not None:
                packed[i, _FSM_DEC + 1] = 1      # resume: force state
                packed[i, _FSM_DEC + 2] = r.pending_fsm_state
                r.pending_fsm_state = None

        use_fsm = self._fsm_any_active()
        self._mh_send(MSG_DECODE, dec_packed=packed, fsm_used=use_fsm)
        (pack, _toks, self.k_pages, self.v_pages, self.token_counts,
         new_state) = self._decode_packed(
            self.params, self.model_config, jnp.asarray(packed),
            self._zeros_B, self._zeros_1, self.k_pages, self.v_pages,
            self.token_counts, self._key,
            self._fsm_args() if use_fsm else None,
        )
        if new_state is not None:
            self._fsm_state = new_state
        t0 = time.perf_counter()
        host = HostSample(np.asarray(jax.device_get(pack)))
        self._device_time_s += time.perf_counter() - t0
        if self.ledger is not None:
            self.ledger.record(t_launch, time.monotonic(),
                               [(r, "decode", 1) for _i, r in active])

        events: list[StepEvent] = []
        for i, r in active:
            self.slot_len[i] += 1  # pending token's KV is now cached
            new = int(host.tokens[i])
            r.pending_token = new
            events += self._emit(r, new, _lp_entry(host, i))
        return events

    # ------------------------------------------------------------------
    # async (pipelined) scheduling
    # ------------------------------------------------------------------

    def _inflight_count(self, slot: int) -> int:
        """In-flight decode steps that will grow THIS slot's current
        request. Steps whose entry at this slot refers to a previous
        (finished/preempted) occupant must not count — they write garbage
        the harvest skips, and counting them would inflate the new
        request's attention length into unwritten positions."""
        cur = self.slots[slot]
        return sum(1 for s in self._inflight
                   for j, r in s.active if j == slot and r is cur)

    def _inflight_counts(self) -> dict:
        """Per-slot in-flight step counts in ONE pass over the pipeline
        (same semantics as _inflight_count, amortized for the launch
        path's B consumers)."""
        counts: dict[int, int] = {}
        for s in self._inflight:
            for j, r in s.active:
                if self.slots[j] is r:
                    counts[j] = counts.get(j, 0) + 1
        return counts

    def _inflight_tokens(self) -> dict:
        """Per-slot in-flight TOKEN counts — like _inflight_counts, but a
        fused multi-step dispatch contributes its planned window size, not
        1. This is what the multi-step launch sizes page allocations and
        window budgets from."""
        counts: dict[int, int] = {}
        for s in self._inflight:
            for j, r in s.active:
                if self.slots[j] is r:
                    p = 1 if s.planned is None else s.planned.get(j, 0)
                    counts[j] = counts.get(j, 0) + p
        return counts

    def _admit_async(self, events: list[StepEvent]):
        """Admission without host sync: prefill up to admit_batch waiting
        same-bucket requests in ONE padded call; first-token reads are
        deferred to _harvest. Returns None or a dict describing the
        admissions for the decode launch's on-device token merge."""
        from llms_on_kubernetes_tpu import faults

        if faults.is_active("queue_stall"):
            # LLMK_FAULT=queue_stall: admission refuses while the flag is
            # set (see _admit_one)
            return None
        if any(s.spec for s in self._inflight):
            # a speculative verify is in flight: its consumed-vs-planned
            # delta is unknown until harvest, so defer admission one step
            # (an admission's decode launch must run the same iteration,
            # and spec dispatches serialize). step() harvests the verify
            # this iteration and admits first thing next iteration — no
            # starvation, bounded by one dispatch.
            return None
        # clear BEFORE scanning: a submit after this point re-sets the flag
        # (at worst a spurious backpressure wakeup), while anything already
        # queued is handled right here
        self._admit_wake.clear()
        picked: list[tuple[int, "Request", bool, list[int]]] = []
        long_pick = None
        with self._lock:
            while self.waiting and len(picked) < self.config.admit_batch:
                slot = self._free_slot()
                if slot is None:
                    break
                req = self.waiting[0]
                if (req.params.grammar is not None
                        and not self._ensure_grammar(req)):
                    break  # all grammar rows pinned; wait
                if req.adapter is not None and not self._ensure_adapter(req):
                    break  # every adapter slot pinned; wait
                resumed = bool(req.output)
                prefill_tokens = req.prompt + (req.output[:-1] if resumed else [])
                n = len(prefill_tokens)
                if self.allocator.pages_needed(n + 1) > self.allocator.pages_per_slot:
                    self.waiting.popleft()
                    events.append(self._finish(req, "length"))
                    continue
                hit = self._adopt_cached_prefix(slot, req, prefill_tokens)
                if (hit > 0 or req.images is not None
                        or n > max(self.config.prefill_buckets)):
                    # cache-hit / multimodal / out-of-bucket prompt: runs
                    # alone (chunk path or mm prefill)
                    if picked or not self.allocator.can_allocate(slot, n + 1):
                        if hit:
                            self.allocator.rollback_adopt(slot)
                        self._host_adopt.pop(slot, None)
                        break  # runs by itself next iteration / wait
                    self.waiting.popleft()
                    self.allocator.allocate(slot, n + 1)
                    if hit:
                        self.allocator.commit_adopt(slot, hit)
                    self._note_admission(req)
                    self.slots[slot] = req
                    req.slot = slot
                    if resumed and req.fsm_row >= 0:
                        self._fsm_replay(req)
                    long_pick = (slot, req, resumed, prefill_tokens, hit)
                    break
                if picked and self._bucket_for(n) != self._bucket_for(
                        len(picked[0][3])):
                    break  # next request needs a different bucket
                if not self.allocator.can_allocate(slot, n + 1):
                    break  # wait for pages to free up
                self.waiting.popleft()
                self.allocator.allocate(slot, n + 1)
                self._note_admission(req)
                # combined hit was 0, so this is counter-only (host miss)
                self._host_kv_commit(slot, req)
                self.slots[slot] = req
                req.slot = slot
                if resumed and req.fsm_row >= 0:
                    self._fsm_replay(req)
                picked.append((slot, req, resumed, prefill_tokens))
        if long_pick is not None:
            slot, req, resumed, prefill_tokens, hit = long_pick
            # upload host-tier pages (if staged) before the chunk prefill
            # below is dispatched — its history attention reads them.
            # Outside the lock: the np.stack memcpy must not block submit()
            self._host_kv_commit(slot, req)
            t_launch = time.monotonic()
            if req.images is not None and hit == 0:
                pack, toks = self._dispatch_mm_prefill(slot, req,
                                                       prefill_tokens)
                n_chunks = 2  # image encode + prefill
            else:
                # cache-hit remainder (pure text for multimodal hits) or
                # an out-of-bucket text prompt
                pack, toks = self._chunked_prefill(slot, req, prefill_tokens,
                                                   start=hit)
                n_chunks = -(-(len(prefill_tokens) - hit)
                             // max(self.config.prefill_buckets))
            if req.cache_salt is not None:
                self.allocator.register_prefix(slot, req.prompt,
                                               salt=req.cache_salt)
            self._busy_until = (max(time.monotonic(), self._busy_until)
                                + 2.0 * n_chunks * self._est_step)
            merge = {"toks": toks, "slots": {}}
            if resumed:
                # no priority read to segment on — the resumed re-prefill's
                # device time folds into the next decode harvest's segment
                req.pending_token = req.output[-1]
                merge["slots"][slot] = (True, req.output[-1], 0)
            else:
                key = -1 - next(self._first_counter)
                self._harvester.push(key, pack, priority=True)
                merge["slots"][slot] = (False, 0, 0)
                self._pending_first.append((req, key, 0))
                if self.ledger is not None:
                    self._ledger_prefills[key] = (
                        t_launch,
                        [(req, max(1, len(prefill_tokens) - hit))])
            return merge
        if not picked:
            return None

        from llms_on_kubernetes_tpu.engine.multihost import MSG_PREFILL

        bucket = max(self._bucket_for(len(p[3])) for p in picked)
        # pad the batch to 1 or admit_batch rows (two executables per bucket)
        K = 1 if len(picked) == 1 else self.config.admit_batch
        pps = self.allocator.pages_per_slot
        tokens = np.zeros((K, bucket), np.int32)
        packed = np.zeros((K, _PRE_COLS + pps), np.int32)
        packed[:, 3] = np.float32(1.0).view(np.int32)  # top_p disabled
        packed[:, _ADP_PRE] = -1                       # padded rows: base
        packed[:, _FSM_PRE:_FSM_PRE + 2] = -1          # padded rows: none
        for row, (slot, req, _resumed, ptoks) in enumerate(picked):
            n = len(ptoks)
            tokens[row, :n] = ptoks
            self._pack_prefill_row(packed, row, req, n, slot)
            self.slot_len[slot] = n

        use_fsm = bool((packed[:, _FSM_PRE] >= 0).any())
        t_launch = time.monotonic()
        self._mh_send(MSG_PREFILL, pre_tokens=tokens, pre_packed=packed,
                      fsm_used=use_fsm)
        (pack, toks, self.k_pages, self.v_pages, self.token_counts,
         new_state) = self._prefill_packed(
            self.params, self.model_config, jnp.asarray(tokens),
            jnp.asarray(packed), self.k_pages, self.v_pages,
            self.token_counts, self._key,
            self._fsm_args() if use_fsm else None,
        )
        if new_state is not None:
            self._fsm_state = new_state
        self._busy_until = (max(time.monotonic(), self._busy_until)
                            + 2.0 * self._est_step)  # prefill ≈ 2 steps
        for slot, req, _resumed, _ptoks in picked:
            self.allocator.register_prefix(slot, req.prompt)
        key = None
        if any(not resumed for _, _, resumed, _ in picked):
            # priority read: first tokens jump the decode-read queue
            key = -1 - next(self._first_counter)
            self._harvester.push(key, pack, priority=True)
            if self.ledger is not None:
                # every picked row rode the dispatch, resumed ones included
                self._ledger_prefills[key] = (
                    t_launch,
                    [(req, max(1, len(ptoks)))
                     for _slot, req, _resumed, ptoks in picked])
        merge = {"toks": toks, "slots": {}}
        for row, (slot, req, resumed, _ptoks) in enumerate(picked):
            if resumed:
                # pending token is already host-known (the last emitted
                # token); the prefill's sampled token is discarded, as in
                # the sync path
                req.pending_token = req.output[-1]
                merge["slots"][slot] = (True, req.output[-1], row)
            else:
                merge["slots"][slot] = (False, 0, row)
                self._pending_first.append((req, key, row))
        return merge

    def _launch_decode_async(self, admitted, events: list[StepEvent]) -> str:
        """Launch one decode step whose input tokens are assembled ON DEVICE
        from the newest in-flight step's output (continuing slots), host
        values (slots with no step in flight), and this step's prefill
        (just-admitted slots). Returns "launched", "paced" (deliberately
        deferred — the device queue is deep enough), or "idle"."""
        if self.config.decode_steps > 1:
            if self._spec is not None:
                st = self._launch_decode_spec(self.config.decode_steps,
                                              admitted, events)
                if st is not None:
                    return st
            return self._launch_decode_multi(self.config.decode_steps,
                                             admitted, events)
        B = self.config.max_decode_slots
        max_len = self.config.max_model_len

        pace = self.config.pace_target_steps
        if pace > 0 and admitted is None and self._inflight:
            # pacing: dispatching now would only deepen the queue a new
            # request's prefill has to wait behind. (A step that just
            # admitted always launches — its decode merges the prefill's
            # sampled tokens.)
            if self._busy_until - time.monotonic() > pace * self._est_step:
                return "paced"

        # grow page tables; drain in-flight work, then preempt, on exhaustion.
        # inflight counts are computed ONCE per pass (a per-slot
        # _inflight_count scan is O(B * depth * B) per launch — measured
        # ~6 ms/step at B=64, a real slice of the step budget on a
        # small-core host) and recomputed only when a drain/preempt
        # changes the in-flight set.
        infl = self._inflight_counts()
        i = 0
        while i < B:
            r = self.slots[i]
            if r is None:
                i += 1
                continue
            need = int(self.slot_len[i]) + infl.get(i, 0) + 1
            if need > max_len:
                i += 1  # rides along idle; finishes by length at harvest
                continue
            try:
                self.allocator.allocate(i, need)
                i += 1
            except MemoryError:
                if self._inflight or self._pending_first:
                    # freeing may come from finishes hiding in unharvested
                    # steps — drain before resorting to preemption
                    events += self._harvest(drain=True)
                    infl = self._inflight_counts()
                    continue
                self._preempt_youngest()
                infl = self._inflight_counts()

        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return "idle"

        packed = self._dec_template(active)
        for i, r in active:
            need = int(self.slot_len[i]) + infl.get(i, 0) + 1
            packed[i, 0] = 0 if need > max_len else need
            if r.fsm_row >= 0 and r.pending_fsm_state is not None:
                packed[i, _FSM_DEC + 1] = 1      # resume: force state
                packed[i, _FSM_DEC + 2] = r.pending_fsm_state
                r.pending_fsm_state = None
            if admitted is not None and i in admitted["slots"]:
                resumed, host_val, row = admitted["slots"][i]
                if resumed:              # resumed: host-known pending token
                    packed[i, 1], packed[i, 2] = 1, host_val
                else:                    # fresh: token sampled by the prefill
                    packed[i, 1], packed[i, 7] = 2, row
            elif infl.get(i, 0) > 0:
                packed[i, 1] = 0         # newest in-flight step's output
            else:
                packed[i, 1], packed[i, 2] = 1, r.pending_token

        from llms_on_kubernetes_tpu.engine.multihost import MSG_DECODE

        last_toks = self._inflight[-1].toks if self._inflight else self._zeros_B
        prefill_toks = admitted["toks"] if admitted is not None else self._zeros_1

        # followers pick the same token references by these flags: their own
        # newest decode output (last_valid) / newest prefill-or-chunk output
        # (use_prefill) are the same global arrays by SPMD determinism
        use_fsm = self._fsm_any_active()
        self._mh_send(MSG_DECODE, dec_packed=packed,
                      last_valid=bool(self._inflight),
                      use_prefill=admitted is not None, fsm_used=use_fsm)
        (pack, toks, self.k_pages, self.v_pages, self.token_counts,
         new_state) = self._decode_packed(
            self.params, self.model_config, jnp.asarray(packed),
            last_toks, prefill_toks, self.k_pages, self.v_pages,
            self.token_counts, self._key,
            self._fsm_args() if use_fsm else None,
        )
        if new_state is not None:
            self._fsm_state = new_state
        seq = next(self._seq_counter)
        step = InflightStep(pack, toks, active, seq,
                            planned={i: 1 for i, _r in active},
                            launched_at=time.monotonic())
        self._inflight.append(step)
        self._harvester.push(seq, pack)
        now = time.monotonic()
        self._busy_until = max(now, self._busy_until) + self._est_step
        return "launched"

    def _launch_decode_multi(self, K: int, admitted,
                             events: list[StepEvent]) -> str:
        """Multi-step variant of _launch_decode_async: one dispatch runs a
        window of up to K decode steps per slot (_decode_multi_packed_step).

        Per slot the launch PLANS p <= K tokens — clipped by the request's
        remaining max_tokens budget (minus tokens already in flight and a
        not-yet-harvested first token) and by max_model_len — allocates
        pages for the whole window up front, and ships p as the row's
        on-device budget. Rows with p == 0 ride along masked (lengths 0).
        The harvest consumes up to p tokens per row; host-side _emit stays
        authoritative for finishes, so a row that stops mid-window simply
        wastes its tail (early-exit accounting)."""
        B = self.config.max_decode_slots
        max_len = self.config.max_model_len

        pace = self.config.pace_target_steps
        if pace > 0 and admitted is None and self._inflight:
            if self._busy_until - time.monotonic() > pace * self._est_step:
                return "paced"

        # plan windows + grow page tables; drain in-flight work, then
        # preempt, on exhaustion (same recovery ladder as the K=1 path).
        # Token-level inflight counts: a planned-but-unharvested window
        # already owns its positions. Stale plan entries survive drains —
        # slot_len + inflight is invariant under harvest (tokens move from
        # in-flight to slot_len one-for-one), and so is the max_tokens
        # budget (output grows by exactly the harvested tokens).
        infl = self._inflight_tokens()
        first_pending = {id(r) for r, _k, _row in self._pending_first}
        plan: dict[int, int] = {}
        i = 0
        while i < B:
            r = self.slots[i]
            if r is None:
                i += 1
                continue
            prior = infl.get(i, 0)
            base0 = int(self.slot_len[i]) + prior + 1
            # a first token still in the priority-read queue will be
            # emitted before any of this window's tokens — budget for it
            extra = 1 if id(r) in first_pending else 0
            budget = r.params.max_tokens - len(r.output) - prior - extra
            p = max(0, min(K, budget, max_len - base0 + 1))
            if p == 0:
                plan[i] = 0
                i += 1
                continue
            try:
                self.allocator.allocate(i, base0 + p - 1)
                plan[i] = p
                i += 1
            except MemoryError:
                if self._inflight or self._pending_first:
                    events += self._harvest(drain=True)
                    infl = self._inflight_tokens()
                    first_pending = {id(r) for r, _k, _row
                                     in self._pending_first}
                    continue
                self._preempt_youngest()
                infl = self._inflight_tokens()

        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return "idle"
        if all(plan.get(i, 0) == 0 for i, _r in active):
            # every row's budget is consumed by in-flight work — a
            # dispatch would be all-masked. The pipeline is non-empty in
            # this state (empty pipeline => budget >= 1), so harvesting
            # makes progress.
            return "paced"

        packed = self._dec_template(active)
        for i, r in active:
            p = plan.get(i, 0)
            packed[i, 0] = 0 if p <= 0 else \
                int(self.slot_len[i]) + infl.get(i, 0) + 1
            packed[i, _BUD_DEC] = p
            if r.fsm_row >= 0 and r.pending_fsm_state is not None:
                packed[i, _FSM_DEC + 1] = 1      # resume: force state
                packed[i, _FSM_DEC + 2] = r.pending_fsm_state
                r.pending_fsm_state = None
            if admitted is not None and i in admitted["slots"]:
                resumed, host_val, row = admitted["slots"][i]
                if resumed:              # resumed: host-known pending token
                    packed[i, 1], packed[i, 2] = 1, host_val
                else:                    # fresh: token sampled by the prefill
                    packed[i, 1], packed[i, 7] = 2, row
            elif infl.get(i, 0) > 0:
                packed[i, 1] = 0         # newest in-flight step's output
            else:
                packed[i, 1], packed[i, 2] = 1, r.pending_token

        last_toks = self._inflight[-1].toks if self._inflight else self._zeros_B
        prefill_toks = admitted["toks"] if admitted is not None else self._zeros_1

        # multihost always clamps decode_steps to 1 in EngineConfig, so
        # this path never needs a broadcast message
        use_fsm = self._fsm_any_active()
        (pack, toks, self.k_pages, self.v_pages, self.token_counts,
         new_state) = self._decode_multi(
            self.params, self.model_config, K, jnp.asarray(packed),
            last_toks, prefill_toks, self.k_pages, self.v_pages,
            self.token_counts, self._key,
            self._fsm_args() if use_fsm else None,
        )
        if new_state is not None:
            self._fsm_state = new_state
        seq = next(self._seq_counter)
        step = InflightStep(pack, toks, active, seq,
                            planned={i: plan.get(i, 0) for i, _r in active},
                            launched_at=time.monotonic())
        self._inflight.append(step)
        self._harvester.push(seq, pack)
        now = time.monotonic()
        self._busy_until = max(now, self._busy_until) + self._est_step
        return "launched"

    def _launch_decode_spec(self, K: int, admitted,
                            events: list[StepEvent]) -> Optional[str]:
        """Try to launch a speculative verify dispatch; returns None to
        fall through to the plain fused window (_launch_decode_multi).

        Spec dispatches SERIALIZE: a window that consumes fewer tokens
        than it planned would break the slot_len + inflight-tokens
        invariant every pipelined launch's position math relies on, so a
        spec step launches only into an empty pipeline (no in-flight
        steps, no pending firsts, no prefill merge) — every slot's last
        token is then host-known (src == 1) and doubles as the drafter's
        context tail. While the verify is in flight the engine reports
        "paced"; admission is deferred by _admit_async for the same
        reason. The accept-ratio policy demotes drafting on adversarial
        traffic, which silently restores the plain fused pipeline."""
        if any(s.spec for s in self._inflight):
            return "paced"          # serialize: wait for the verify
        if admitted is not None:
            return None             # the admission's merge launches NOW
        if not self._spec.policy.should_draft():
            return None
        if self._inflight or self._pending_first:
            # drafting needs every slot's committed tail host-known: pace
            # until the pipeline drains (each spec dispatch then carries
            # up to K tokens, which is what pipelining amortized)
            return "paced"
        B = self.config.max_decode_slots
        max_len = self.config.max_model_len

        # plan windows + grow page tables (the multi ladder, with an empty
        # pipeline: MemoryError goes straight to preemption)
        plan: dict[int, int] = {}
        i = 0
        while i < B:
            r = self.slots[i]
            if r is None:
                i += 1
                continue
            base0 = int(self.slot_len[i]) + 1
            budget = r.params.max_tokens - len(r.output)
            p = max(0, min(K, budget, max_len - base0 + 1))
            plan[i] = p
            if p == 0:
                i += 1
                continue
            try:
                self.allocator.allocate(i, base0 + p - 1)
                i += 1
            except MemoryError:
                self._preempt_youngest()

        active = [(i, r) for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return None
        # draft only rows with window room (p >= 2) and a committed tail
        ctxs: list = [None] * B
        for i, r in active:
            if plan.get(i, 0) >= 2 and r.pending_token >= 0:
                ctxs[i] = np.asarray(r.prompt + r.output, np.int32)
        if not any(c is not None for c in ctxs):
            return None               # no row has draft room: plain window
        proposals = self._spec.propose_batch(ctxs)
        drafted: dict[int, int] = {}
        D = K - 1
        ext = np.full((B, D), -1, np.int32)
        for i, _r in active:
            d = proposals[i][:max(0, plan.get(i, 0) - 1)]
            if d.size:
                ext[i, :d.size] = d
                drafted[i] = int(d.size)
        if not drafted:
            # an attempted-but-empty draft pass is evidence against this
            # traffic: feed the policy so adversarial streams demote to
            # the pipelined plain window instead of serializing forever
            self._spec.policy.note_empty()
            return None               # nothing proposed: plain window

        packed = self._dec_template(active)
        for i, r in active:
            p = plan.get(i, 0)
            packed[i, 0] = 0 if p <= 0 else int(self.slot_len[i]) + 1
            packed[i, _BUD_DEC] = p
            if r.fsm_row >= 0 and r.pending_fsm_state is not None:
                packed[i, _FSM_DEC + 1] = 1      # resume: force state
                packed[i, _FSM_DEC + 2] = r.pending_fsm_state
                r.pending_fsm_state = None
            packed[i, 1], packed[i, 2] = 1, r.pending_token
        full = np.concatenate([packed, ext], axis=1)

        use_fsm = self._fsm_any_active()
        (pack, toks, self.k_pages, self.v_pages, self.token_counts,
         new_state) = self._decode_spec(
            self.params, self.model_config, K, jnp.asarray(full),
            self.k_pages, self.v_pages, self.token_counts, self._key,
            self._fsm_args() if use_fsm else None,
        )
        if new_state is not None:
            self._fsm_state = new_state
        seq = next(self._seq_counter)
        step = InflightStep(pack, toks, active, seq,
                            planned={i: plan.get(i, 0) for i, _r in active},
                            spec=True, drafted=drafted,
                            launched_at=time.monotonic())
        self._inflight.append(step)
        self._harvester.push(seq, pack)
        now = time.monotonic()
        self._busy_until = max(now, self._busy_until) + self._est_step
        return "launched"

    def _harvest(self, drain: bool) -> list[StepEvent]:
        """Consume host copies of completed device work from the harvester
        thread, in dispatch order, WITHOUT blocking on device execution.

        The engine thread blocks in exactly two cases: ``drain`` (state
        inspection / shutdown / memory pressure needs every result), and
        backpressure (the pipeline holds ``async_depth`` unharvested decode
        steps — launching more would speculate unboundedly). Everything
        else — including admission of new requests and their prefill
        dispatch — proceeds while the harvester waits out the device and
        the tunnel round trip. This is what bounds gateway TTFT: a new
        request's prefill no longer queues behind a blocking batched read
        of the whole pipeline."""
        events: list[StepEvent] = []
        if not self._inflight and not self._pending_first:
            return events
        depth = max(1, self.config.async_depth)
        budget = self._stall_budget()
        n_steps = 0
        while True:
            n_steps += self._collect_ready(events)
            if drain:
                if not self._inflight and not self._pending_first:
                    break
            elif len(self._inflight) < depth:
                break
            # blocked: wait for whatever gates the head. If the oldest
            # step's request still awaits its FIRST token (its priority
            # read hasn't landed), wait for that key — consuming the step
            # early would let a stale first overwrite pending_token later
            # and feed the model a wrong input token.
            key = self._head_blocking_first()
            if key is not None:
                self._harvester.wait_key(key, timeout_s=budget)
                continue
            if self._inflight:
                k = (len(self._inflight) if drain
                     else len(self._inflight) - (depth - 1))
                self._harvester.wait_done(
                    self._inflight[k - 1].seq,
                    wake=None if drain else self._admit_wake,
                    timeout_s=budget)
                if not drain and self._admit_wake.is_set():
                    # a submission wants admission NOW; collect whatever
                    # completed and hand control back (pipeline may sit
                    # one step over depth for one iteration)
                    n_steps += self._collect_ready(events)
                    break
                continue
            # drain with only firsts left
            self._harvester.wait_key(self._pending_first[0][1],
                                     timeout_s=budget)
        # pacing calibration: completion spacing per decode step bounds the
        # device step time from ABOVE (reads add latency, never remove it),
        # so track the MINIMUM with slow upward drift. A mean/EMA here is
        # unstable: when reads are the bottleneck the spacing reflects the
        # read path, the estimate inflates, pacing launches slower, spacing
        # confirms the inflated estimate, and the pipeline starves
        # (observed: 4x throughput collapse).
        if n_steps > 0:
            now = time.monotonic()
            if self._last_harvest_t is not None:
                gap = (now - self._last_harvest_t) / n_steps
                if 0.0 < gap < 0.5:
                    if gap < self._est_step:
                        self._est_step = gap
                    else:
                        self._est_step = min(self._est_step * 1.02, gap)
            self._last_harvest_t = now
        elif not self._inflight:
            self._last_harvest_t = None  # idle: next spacing sample invalid
        return events

    def _head_blocking_first(self) -> Optional[int]:
        """The pending-first key gating the OLDEST in-flight step, or None.

        A decode step must not be consumed before its request's first
        token: processing it early advances slot_len/pending_token, and
        the late first would then rewind pending_token to the prompt's
        sampled token — feeding a stale input to the next host-value
        decode launch (observed as diverged generations)."""
        if not self._inflight or not self._harvester.is_done(
                self._inflight[0].seq):
            return None
        waiting = {id(r): k for r, k, _ in self._pending_first}
        for slot, req in self._inflight[0].active:
            if not req.finished and req.slot == slot and id(req) in waiting:
                return waiting[id(req)]
        return None

    def _collect_ready(self, events: list[StepEvent]) -> int:
        """Non-blocking: consume every completed result whose ordering
        constraints are satisfied — firsts in FIFO order, then steps in
        dispatch order while not gated by a pending first. Returns the
        number of decode steps consumed (pacing calibration)."""
        # firsts are per-request-independent results: with overlapped
        # harvester readers (LLMK_HARVEST_READERS >= 2) a LATER priority
        # batch can land before an earlier in-flight one, so release every
        # completed entry rather than stopping at the first not-done key —
        # a FIFO prefix scan would couple independent requests' TTFT
        # (round-3 advisor finding)
        done_entries, still = [], []
        for entry in self._pending_first:
            (done_entries if self._harvester.key_done(entry[1])
             else still).append(entry)
        for req, key, row in done_entries:
            if req.finished:
                continue
            host = HostSample(np.asarray(self._harvester.get(key)))
            tok = int(host.tokens[row])
            req.pending_token = tok
            req.first_token_at = time.monotonic()
            events += self._emit(req, tok, _lp_entry(host, row))
        if done_entries:
            self._pending_first = still
            done_keys = {k for _, k, _ in done_entries}
            for k in done_keys - {k for _, k, _ in still}:
                led = self._ledger_prefills.pop(k, None)
                if led is not None and self.ledger is not None:
                    t_launch, rows = led
                    self.ledger.record(
                        t_launch, self._harvester.done_time(k),
                        [(req, "prefill", n) for req, n in rows])
                self._harvester.discard_key(k)

        processed = -1
        n_steps = 0
        while self._inflight and self._harvester.is_done(self._inflight[0].seq):
            if self._head_blocking_first() is not None:
                break  # the step's request still awaits its first token
            step = self._inflight.popleft()
            res = self._harvester.get(step.seq)
            accept = None
            if isinstance(res, (tuple, list)):   # spec: (packs, accept)
                res, accept = res
                accept = np.asarray(accept)
            arr = np.asarray(res)
            if arr.ndim == 2:    # single-step pack [B, W] => window of 1
                arr = arr[None]
            hosts = [HostSample(arr[k]) for k in range(arr.shape[0])]
            processed = step.seq
            n_steps += 1
            consumed_total = wasted = max_consumed = 0
            spec_accepted = 0
            led_rows: list = []
            for slot, req in step.active:
                p = 1 if step.planned is None else step.planned.get(slot, 0)
                if p <= 0:
                    continue
                waste_phase = ("spec_waste"
                               if step.spec and step.drafted
                               and slot in step.drafted else "early_exit")
                # a spec row consumes only its device-verified prefix: the
                # suffix rows after a draft mismatch hold tokens sampled
                # from logits conditioned on the REJECTED draft — garbage
                # by construction, discarded exactly like an early exit
                # (the rejected tail still counts as wasted window)
                cap = p if accept is None else min(p, int(accept[slot]))
                # skip slots whose request finished/aborted/was preempted
                # after this step launched — their sampled tokens are
                # garbage (and the whole window is wasted speculation)
                if req.finished or req.slot != slot:
                    wasted += p
                    led_rows.append((req, waste_phase, p))
                    continue
                consumed = 0
                for k in range(cap):
                    self.slot_len[slot] += 1
                    tok = int(hosts[k].tokens[slot])
                    req.pending_token = tok
                    events += self._emit(req, tok, _lp_entry(hosts[k], slot))
                    consumed += 1
                    if req.finished:
                        # the device masked this row right here too
                        # (stop id / budget); its tail is wasted window
                        break
                consumed_total += consumed
                wasted += p - consumed
                led_rows.append((req, "decode", consumed))
                if p > consumed:
                    led_rows.append((req, waste_phase, p - consumed))
                max_consumed = max(max_consumed, consumed)
                if step.spec and step.drafted and slot in step.drafted:
                    # accepted drafts = consumed tokens minus the one the
                    # plain path would have produced anyway
                    spec_accepted += max(0, consumed - 1)
            if self.ledger is not None:
                self.ledger.record(
                    step.launched_at, self._harvester.done_time(step.seq),
                    led_rows, window=arr.shape[0])
            self.decode_dispatches += 1
            self.decode_tokens += consumed_total
            self.early_exit_steps += wasted
            self.steps_obs.append(max_consumed)
            if step.spec:
                drafted_n = sum((step.drafted or {}).values())
                self.spec_dispatches += 1
                self.spec_drafted_tokens += drafted_n
                self.spec_accepted_tokens += spec_accepted
                if self._spec is not None:
                    self._spec.policy.note(drafted_n, spec_accepted)
            elif self._spec is not None:
                self._spec.policy.tick()
        if processed >= 0:
            self._harvester.discard_upto(processed)
        return n_steps

    def _drain_async(self) -> list[StepEvent]:
        """Synchronize: harvest everything in flight (used before state
        inspection / shutdown)."""
        events = self._harvest(drain=True)
        for ev in events:
            payload = (ev.new_tokens, ev.finished, ev.finish_reason)
            ev.request.events.put(payload)
            if ev.request.on_event is not None:
                ev.request.on_event(payload)
        return events

    # ------------------------------------------------------------------
    # convenience
    # ------------------------------------------------------------------

    def generate(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        adapter: Optional[str] = None,
    ) -> list[int]:
        """Synchronous single-request generation (drives the scheduler)."""
        req = self.submit(prompt, params, adapter=adapter)
        while not req.finished:
            self.step()
        return req.output

    def score_prompt(self, prompt: list[int]):
        """Per-position prompt logprobs (the OpenAI ``echo+logprobs`` /
        vLLM ``prompt_logprobs`` surface): returns
        (token_logprobs [len-1], top_ids [len, K], top_logprobs [len, K])
        where token_logprobs[i] scores prompt[i+1]; K is always
        sampling.LOGPROB_TOPK (a per-request k would compile a separate
        executable per value — callers slice).

        Thread-safe against the engine loop: the scoring forward is
        cache-free (decoder.forward_score — writes go to a private dummy
        trash pool), touches no donated engine state, and the device
        serializes it between scheduler steps. Unsupported on seq-parallel
        meshes (the scoring pool is unsharded). Under multi-host the call
        is announced over the packed broadcast protocol (MSG_SCORE) like
        any other step, so every process enters the same forward_score
        executable and the pod group never deadlocks."""
        from llms_on_kubernetes_tpu.engine.sampling import LOGPROB_TOPK
        from llms_on_kubernetes_tpu.parallel.mesh import AXIS_SEQ

        if self.mesh is not None and int(self.mesh.shape.get(AXIS_SEQ, 1)) > 1:
            raise ValueError("prompt scoring is not supported under "
                             "sequence-parallel serving")
        if len(prompt) > self.config.max_model_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds max_model_len="
                f"{self.config.max_model_len}")
        n = len(prompt)
        # pad to a prefill bucket, or — past the largest bucket — to a
        # multiple of it: unbounded per-length shapes would compile (and
        # cache) one executable per distinct long-prompt length, and odd
        # lengths fall off the flash kernel onto the [T, T]-materializing
        # reference attention
        bucket = next((b for b in self.config.prefill_buckets if n <= b),
                      None)
        if bucket is None:
            big = max(self.config.prefill_buckets)
            bucket = -(-n // big) * big
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        if self.config.multihost:
            # announce + ship the token row so follower pods enter the
            # same forward_score executable (SPMD — a coordinator-only
            # program over globally sharded params would deadlock)
            from llms_on_kubernetes_tpu.engine import multihost as mh

            self._mh_send(mh.MSG_SCORE, score=(bucket, n))
            mh.send_score_payload(tokens)
        nxt_lp, top_ids, top_lp = self._score_jit(
            self.params, self.model_config, jnp.asarray(tokens),
            jnp.asarray([n], jnp.int32), LOGPROB_TOPK)
        host = jax.device_get((nxt_lp, top_ids, top_lp))
        return (host[0][0, :n - 1].tolist(),
                host[1][0, :n].tolist(), host[2][0, :n].tolist())

"""On-device batched token sampling.

One fused function handles the whole decode batch with PER-SLOT sampling
parameters (temperature / top-k / top-p as [B] arrays), so heterogeneous
requests share one compiled step — the continuous-batching analogue of what
the reference's vLLM image did per sequence (SURVEY §2.3 row 1).

TPU-first: everything stays on device inside the jitted decode step; only the
sampled token ids ([B] int32) come back to the host each step. Greedy is
expressed as temperature==0 via masking, not Python branching, so one
executable covers all modes.

Top-k/top-p both work on a single descending sort of the logits (O(V log V),
fused by XLA); the categorical draw uses the Gumbel trick on the masked,
renormalized logits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -2.0e38


def sample(
    logits: jnp.ndarray,       # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] float32; 0 => greedy
    top_k: jnp.ndarray,        # [B] int32; 0 or >=V => disabled
    top_p: jnp.ndarray,        # [B] float32; 1.0 => disabled
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (tokens [B] int32, logprobs of the sampled tokens [B] f32)."""
    B, V = logits.shape
    logits = logits.astype(jnp.float32)

    # --- filtering in sorted space ------------------------------------
    sort_idx = jnp.argsort(-logits, axis=-1)                 # [B, V] desc
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)

    rank = jnp.arange(V, dtype=jnp.int32)[None, :]           # [1, V]
    k = jnp.where(top_k <= 0, V, top_k)[:, None]             # [B, 1]
    keep_k = rank < k

    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprob = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens whose cumulative prob *before* them is < top_p (always
    # keeps the argmax token)
    keep_p = (cumprob - sorted_probs) < top_p[:, None]

    keep = keep_k & keep_p
    masked_sorted = jnp.where(keep, sorted_logits, NEG_INF)

    # --- draw ----------------------------------------------------------
    safe_temp = jnp.maximum(temperature, 1e-6)[:, None]
    gumbel = jax.random.gumbel(key, (B, V), jnp.float32)
    perturbed = masked_sorted / safe_temp + gumbel
    sampled_rank = jnp.argmax(perturbed, axis=-1)            # [B]

    greedy_rank = jnp.zeros((B,), sampled_rank.dtype)        # sorted => rank 0
    chosen_rank = jnp.where(temperature <= 0.0, greedy_rank, sampled_rank)

    tokens = jnp.take_along_axis(sort_idx, chosen_rank[:, None], axis=-1)[:, 0]
    logprobs_all = jax.nn.log_softmax(logits, axis=-1)
    logprobs = jnp.take_along_axis(logprobs_all, tokens[:, None], axis=-1)[:, 0]
    return tokens.astype(jnp.int32), logprobs


def make_sampling_arrays(requests, num_slots: int):
    """Host helper: build [num_slots] parameter arrays from per-slot request
    objects (None => defaults)."""
    import numpy as np

    temps = np.zeros((num_slots,), np.float32)
    top_ks = np.zeros((num_slots,), np.int32)
    top_ps = np.ones((num_slots,), np.float32)
    for i, r in enumerate(requests):
        if r is None:
            continue
        temps[i] = r.temperature
        top_ks[i] = r.top_k
        top_ps[i] = r.top_p
    return temps, top_ks, top_ps

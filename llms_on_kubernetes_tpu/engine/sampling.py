"""On-device batched token sampling.

One fused function handles the whole decode batch with PER-SLOT sampling
parameters (temperature / top-k / top-p as [B] arrays), so heterogeneous
requests share one compiled step — the continuous-batching analogue of what
the reference's vLLM image did per sequence (SURVEY §2.3 row 1).

TPU-first: everything stays on device inside the jitted decode step; only the
sampled token ids ([B] int32) come back to the host each step. Greedy is
expressed as temperature==0 via masking, not Python branching, so one
executable covers all modes.

Top-k/top-p work on a FIXED top-MAX_CANDIDATES candidate set. On TPU the
set is extracted with ``lax.approx_max_k`` (the hardware-native bucketed
reduction; exact ``lax.top_k`` measured 2.6 ms/step for a 128K vocab on
v5e, approx ~0) — its ~0.95 recall means a true top-i candidate can
occasionally be replaced by the next-best one from its bucket, for BOTH
the top_k filter and the top-p nucleus. Greedy is always exact: the
global argmax is provably rank 0 of approx_max_k's output (it is its own
bucket's maximum, and the cross-bucket top-k is exact). On CPU the
extraction is exact ``lax.top_k``. The candidate-set cap itself is a
hard bound: top_k > MAX_CANDIDATES is REJECTED at Engine.submit() (400
at the API), and top-p loses only the tail mass beyond 64 tokens — the
same tradeoff TPU serving stacks standardly make. The categorical draw
uses the Gumbel trick on the masked, renormalized candidate logits.

Reproducibility caveat: because the TPU path extracts candidates with
``approx_max_k`` and the CPU path with exact ``top_k``, a SEEDED non-greedy
request is reproducible within a backend but not necessarily ACROSS
CPU/TPU. Set ``LLMK_EXACT_SAMPLING=1`` to force exact ``lax.top_k`` on TPU
(costs ~2.6 ms/step at 128K vocab) when cross-backend determinism matters
more than throughput.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

NEG_INF = -2.0e38
# Sampling candidate pool per slot. top_k values above this are REJECTED
# at the API layer (400) — silent clamping would change the sampling
# semantics the client asked for; top-p nucleus truncation beyond the pool
# drops ~zero probability mass.
MAX_CANDIDATES = 64
# Top alternatives returned per sampled token (OpenAI `logprobs`/
# `top_logprobs` caps at 5; 8 leaves headroom and rides the same
# device->host read as the token ids).
LOGPROB_TOPK = 8


def sample(
    logits: jnp.ndarray,       # [B, V] float32
    keys: jax.Array,           # [B] PRNG keys (one per slot) or one scalar key
    temperature: jnp.ndarray,  # [B] float32; 0 => greedy
    top_k: jnp.ndarray,        # [B] int32; 0 or >=V => disabled
    top_p: jnp.ndarray,        # [B] float32; 1.0 => disabled
    penalties: "tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray] | None" = None,
    bias: "tuple[jnp.ndarray, jnp.ndarray] | None" = None,
    allowed: "jnp.ndarray | None" = None,
) -> "SampleResult":
    """Returns a SampleResult (tokens, chosen logprobs, top-K alternatives).

    Per-slot keys make a request's sampled stream a function of its own
    (seed, position) only — batch composition can never change what a
    request samples (and the OpenAI ``seed`` parameter works).

    ``penalties`` = (presence [B], frequency [B], counts [B, V] int32):
    OpenAI presence/frequency penalties over the OUTPUT tokens generated
    so far (the engine maintains ``counts``). Applied to the raw logits
    before candidate extraction, so the penalized distribution drives
    top-k/top-p and the reported logprobs — vLLM semantics.

    ``bias`` = (ids [B, N] int32, values [B, N] float32): the OpenAI
    ``logit_bias`` map, added to the raw logits before extraction (so a
    +100 bias forces and a -100 bias bans, vLLM semantics). Padding
    entries carry id -1 and are dropped by the scatter.

    ``allowed`` [B, V] bool: grammar-constrained decoding's per-step
    token mask (engine/grammar.py). Applied AFTER bias — a +100
    logit_bias must not defeat a grammar guarantee — and before
    candidate extraction, so reported logprobs renormalize over the
    allowed set (guided-decoding semantics)."""
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    if penalties is not None:
        presence, frequency, counts = penalties
        c = counts.astype(jnp.float32)
        logits = logits - presence[:, None] * (c > 0) - frequency[:, None] * c
    if bias is not None:
        b_ids, b_vals = bias
        rows = jnp.broadcast_to(
            jnp.arange(B, dtype=jnp.int32)[:, None], b_ids.shape)
        # padding id -1 would WRAP to column V-1 (jax normalizes negative
        # indices before mode="drop" applies — verified), so zero the
        # padded values explicitly; mode="drop" still guards any
        # out-of-range positive id
        b_vals = jnp.where(b_ids >= 0, b_vals.astype(jnp.float32), 0.0)
        logits = logits.at[rows, jnp.maximum(b_ids, 0)].add(
            b_vals, mode="drop")
    if allowed is not None:
        logits = jnp.where(allowed, logits, NEG_INF)
    C = min(MAX_CANDIDATES, V)

    # --- candidate extraction (sorted descending) ---------------------
    # TPU: approx_max_k is the hardware-native bucketed reduction (exact
    # top_k measured 2.6 ms/step at 128K vocab; approx ~free). Recall
    # caveats and the greedy-exactness argument: module docstring.
    exact = os.environ.get("LLMK_EXACT_SAMPLING", "0") == "1"
    if jax.default_backend() == "tpu" and V > 4 * C and not exact:
        cand_logits, cand_idx = jax.lax.approx_max_k(logits, C)
    else:
        cand_logits, cand_idx = jax.lax.top_k(logits, C)     # [B, C] each

    rank = jnp.arange(C, dtype=jnp.int32)[None, :]           # [1, C]
    k = jnp.where(top_k <= 0, C, jnp.minimum(top_k, C))[:, None]
    keep_k = rank < k

    # softmax over the FULL vocab (so probabilities and the top-p cut are
    # computed against the true distribution, not the truncated one)
    lse = jax.nn.logsumexp(logits, axis=-1, keepdims=True)   # [B, 1]
    cand_probs = jnp.exp(cand_logits - lse)                  # [B, C]
    cumprob = jnp.cumsum(cand_probs, axis=-1)
    # keep tokens whose cumulative prob *before* them is < top_p (always
    # keeps the argmax token)
    keep_p = (cumprob - cand_probs) < top_p[:, None]

    keep = keep_k & keep_p
    masked = jnp.where(keep, cand_logits, NEG_INF)           # [B, C]

    # --- draw ----------------------------------------------------------
    safe_temp = jnp.maximum(temperature, 1e-6)[:, None]
    if keys.ndim == 0:  # single key: legacy batch-wide draw
        gumbel = jax.random.gumbel(keys, (B, C), jnp.float32)
    else:
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (C,), jnp.float32))(keys)
    perturbed = masked / safe_temp + gumbel
    sampled_rank = jnp.argmax(perturbed, axis=-1)            # [B]

    # rank 0 is the EXACT argmax even under approx_max_k: its algorithm
    # takes per-shard maxima then an exact top-k over them, and the global
    # maximum is always its shard's maximum — recall loss only affects
    # lower ranks. So greedy stays exact on both extraction paths.
    greedy_rank = jnp.zeros((B,), sampled_rank.dtype)        # sorted => rank 0
    chosen_rank = jnp.where(temperature <= 0.0, greedy_rank, sampled_rank)

    tokens = jnp.take_along_axis(cand_idx, chosen_rank[:, None], axis=-1)[:, 0]
    chosen_logit = jnp.take_along_axis(cand_logits, chosen_rank[:, None],
                                       axis=-1)
    logprobs = (chosen_logit - lse)[:, 0]
    K = min(LOGPROB_TOPK, C)
    return SampleResult(
        tokens=tokens.astype(jnp.int32),
        logprobs=logprobs,
        top_ids=cand_idx[:, :K].astype(jnp.int32),
        top_logprobs=cand_logits[:, :K] - lse,
    )


@jax.tree_util.register_pytree_node_class
class SampleResult:
    """Per-step sampling outputs (a pytree, so it flows through jit).

    tokens [B] int32; logprobs [B] f32 (of the sampled token); top_ids /
    top_logprobs [B, LOGPROB_TOPK] — the highest-probability alternatives
    (sorted desc), for the OpenAI ``logprobs`` surface. All four ride one
    device->host transfer at harvest time."""

    def __init__(self, tokens, logprobs, top_ids, top_logprobs):
        self.tokens = tokens
        self.logprobs = logprobs
        self.top_ids = top_ids
        self.top_logprobs = top_logprobs

    def tree_flatten(self):
        return (self.tokens, self.logprobs, self.top_ids, self.top_logprobs), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def host_pack(self) -> jnp.ndarray:
        """All four outputs as ONE [B, 2 + 2K] int32 array (floats ride
        bitcast). The tunnel pays a per-ARRAY cost on device->host reads
        (measured ~6 ms/leaf mid-pipeline): reading one packed array per
        step instead of four leaves is a ~4x cut in the harvester's host
        work — which is what bounds throughput on small-core hosts."""
        lp = jax.lax.bitcast_convert_type(self.logprobs, jnp.int32)
        tlp = jax.lax.bitcast_convert_type(self.top_logprobs, jnp.int32)
        return jnp.concatenate(
            [self.tokens[:, None], lp[:, None], self.top_ids, tlp], axis=1)


class HostSample:
    """Host-side view of a device_get of SampleResult.host_pack()."""

    __slots__ = ("tokens", "logprobs", "top_ids", "top_logprobs")

    def __init__(self, arr):
        import numpy as np

        K = (arr.shape[1] - 2) // 2
        self.tokens = arr[:, 0]
        self.logprobs = np.ascontiguousarray(arr[:, 1]).view(np.float32)
        self.top_ids = arr[:, 2:2 + K]
        self.top_logprobs = np.ascontiguousarray(
            arr[:, 2 + K:2 + 2 * K]).view(np.float32)


def make_sampling_arrays(requests, num_slots: int):
    """Host helper: build [num_slots] parameter arrays from per-slot request
    objects (None => defaults)."""
    import numpy as np

    temps = np.zeros((num_slots,), np.float32)
    top_ks = np.zeros((num_slots,), np.int32)
    top_ps = np.ones((num_slots,), np.float32)
    for i, r in enumerate(requests):
        if r is None:
            continue
        temps[i] = r.temperature
        top_ks[i] = r.top_k
        top_ps[i] = r.top_p
    return temps, top_ks, top_ps

"""Goodput ledger: per-request chip-time attribution + MFU/MBU accounting.

The engine batches many tenants into one device dispatch, fuses K-token
decode windows, speculates, and early-exits masked rows — so wall-clock
and per-stream latency no longer say where chip time actually went.
This module answers that question with an explicit accounting identity:

    attributed (prefill + decode) + wasted (spec_waste + early_exit)
      + idle  ==  ledger window (first launch -> last completion)

Every dispatch the engine launches is recorded here with its launch and
completion timestamps. Because the device executes dispatches serially,
the busy interval attributable to dispatch N is the segment from the
previous dispatch's completion (or N's own launch, whichever is later)
to N's completion — segments never overlap, gaps between them are idle,
and the sum conserves wall time by construction (ci.sh gates this on
the smoke run). Each segment is then split across the rows that rode
the dispatch, weighted by planned window tokens: consumed tokens bill
to the stream's ``prefill``/``decode`` phase, speculative rejected
tails to ``spec_waste``, and masked/abandoned rows to ``early_exit`` —
waste is still booked against the request and tenant that caused it,
but never counted as useful stream time.

FLOPs/bytes ride the same records (2 * active-params per token for
compute; weight + KV-page traffic for memory), giving the ``llm_mfu_
ratio`` / ``llm_mbu_ratio`` gauges (Chowdhery et al., PaLM 2022). On
CPU smoke runs the peak table falls back to a nominal figure — the
ratios are plumbing-real but not hardware-meaningful there (see
k8s/tpu-models/README.md "Goodput & chip-time accounting").

:class:`StepAnomalyDetector` watches the same per-dispatch durations
with an EWMA mean/variance + z-score test; the serving loop turns a
sustained anomaly into ONE bounded, rate-limited profiler capture
(``llm_auto_profile_total{reason="step_anomaly"}``) while the slowness
is still live.
"""

from __future__ import annotations

import collections
import math
import os
import threading
from typing import Any, Optional

PHASES = ("prefill", "decode", "spec_waste", "early_exit")
WASTE_PHASES = ("spec_waste", "early_exit")

# device_kind substring -> (peak dense bf16 FLOP/s, peak HBM bytes/s).
# Nominal public figures; overridable via LLMK_PEAK_TFLOPS / LLMK_PEAK_GBPS
# for hardware the table has never heard of.
_PEAK_TABLE = (
    ("v6e", (918e12, 1640e9)),
    ("v5p", (459e12, 2765e9)),
    ("v5e", (197e12, 819e9)),  # matches "v5 lite" kinds via the v5e alias
    ("v5litepod", (197e12, 819e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (123e12, 900e9)),
)
# CPU / unknown accelerator: a deliberately small nominal peak so smoke
# MFU is a sane nonzero ratio instead of ~0 against a TPU-sized peak.
_PEAK_FALLBACK = (5e11, 5e10)


def detect_peak() -> tuple[float, float]:
    """(peak FLOP/s, peak bytes/s) for the local accelerator.

    Env overrides win; else the device kind maps through the table;
    else the nominal CPU fallback. Never raises — the ledger must work
    wherever the engine does."""
    flops = os.environ.get("LLMK_PEAK_TFLOPS")
    gbps = os.environ.get("LLMK_PEAK_GBPS")
    if flops and gbps:
        try:
            return float(flops) * 1e12, float(gbps) * 1e9
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
        for key, peaks in _PEAK_TABLE:
            if key in kind:
                return peaks
    except Exception:
        pass
    return _PEAK_FALLBACK


def _active_params(cfg: Any) -> int:
    """Parameters touched per token: for MoE, only the routed experts'
    share of the expert MLPs counts (num_params sums all experts)."""
    n = int(cfg.num_params)
    if getattr(cfg, "is_moe", False) and cfg.num_experts > 0:
        d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
        all_mlp = 3 * d * f * cfg.num_experts
        active_mlp = 3 * d * f * cfg.num_experts_per_tok
        n -= L * (all_mlp - active_mlp)
    return n


class StepAnomalyDetector:
    """EWMA + z-score detector over per-dispatch device time.

    ``observe(duration_s, now)`` returns True exactly when a trigger
    fires: z-score above ``threshold`` for ``sustain`` consecutive
    samples, after ``warmup`` samples established a baseline, and not
    within ``cooldown_s`` of the previous trigger (the rate limit the
    auto-profiler relies on). Anomalous samples do NOT update the EWMA —
    otherwise a sustained slowdown would teach the baseline to accept
    itself before the sustain count is reached."""

    def __init__(self, threshold: float = 4.0, sustain: int = 3,
                 cooldown_s: float = 600.0, warmup: int = 12,
                 alpha: float = 0.05):
        self.threshold = float(threshold)
        self.sustain = max(1, int(sustain))
        self.cooldown_s = float(cooldown_s)
        self.warmup = max(2, int(warmup))
        self.alpha = float(alpha)
        self._mean = 0.0
        self._var = 0.0
        self._n = 0
        self._streak = 0
        self._cooldown_until: Optional[float] = None
        self.triggers = 0

    def zscore(self, x: float) -> float:
        if self._n < self.warmup:
            return 0.0
        # variance floor: a perfectly steady baseline (tests, mocked
        # clocks) must still register a spike instead of dividing by ~0
        std = math.sqrt(max(self._var, (0.05 * self._mean) ** 2, 1e-12))
        return (x - self._mean) / std

    def observe(self, duration_s: float, now: float) -> bool:
        z = self.zscore(duration_s)
        anomalous = self._n >= self.warmup and z > self.threshold
        if anomalous:
            self._streak += 1
        else:
            self._streak = 0
            d = duration_s - self._mean
            a = self.alpha if self._n >= self.warmup else max(
                self.alpha, 1.0 / (self._n + 1))
            self._mean += a * d
            self._var = (1.0 - a) * (self._var + a * d * d)
            self._n += 1
        if self._streak < self.sustain:
            return False
        if (self._cooldown_until is not None
                and now < self._cooldown_until):
            return False
        self._cooldown_until = now + self.cooldown_s
        self._streak = 0
        self.triggers += 1
        return True


class GoodputLedger:
    """Chip-time attribution for one engine (see module docstring).

    All mutation happens on the engine thread via :meth:`record`;
    readers (the serving loop's metrics drain, bench, /metrics
    callbacks) take the same lock through :meth:`snapshot` /
    :meth:`utilization`, so a scrape never sees a half-applied record.
    """

    def __init__(self, model_config: Any,
                 detector: Optional[StepAnomalyDetector] = None,
                 peak_flops: Optional[float] = None,
                 peak_bytes_s: Optional[float] = None):
        pf, pb = (peak_flops, peak_bytes_s)
        if pf is None or pb is None:
            dpf, dpb = detect_peak()
            pf, pb = pf or dpf, pb or dpb
        self.peak_flops = float(pf)
        self.peak_bytes_s = float(pb)
        params = _active_params(model_config)
        dtype_bytes = 2 if "16" in str(model_config.dtype) else 4
        # compute: the standard 2*N MAC count per token (PaLM appendix B;
        # attention-score FLOPs are context-dependent and O(few %) at
        # serving batch sizes, so the weight term is the estimate)
        self.flops_per_token = 2.0 * params
        self.param_bytes = float(params * dtype_bytes)
        # KV traffic per token-step: one K+V page-write plus (amortized)
        # the read of its own history — bounded below by the write
        self.kv_bytes_per_token = float(
            2 * model_config.num_layers * model_config.kv_dim * dtype_bytes)

        self.detector = detector
        self._lock = threading.Lock()
        self._last_complete: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.dispatches = 0
        self.busy_ms = 0.0
        self.idle_ms = 0.0
        self.phase_ms: dict[str, float] = {p: 0.0 for p in PHASES}
        self.tenant_ms: dict[tuple[str, str], float] = {}
        self.flops = 0.0
        self.hbm_bytes = 0.0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self.anomaly_events = 0
        self._anomaly_pending = False
        # (t_done, duration_s, flops, bytes) of recent dispatches — the
        # rolling window the MFU/MBU gauges are computed over
        self._recent: "collections.deque[tuple]" = collections.deque(
            maxlen=2048)

    # -- recording (engine thread) -------------------------------------

    def record(self, t_launch: float, t_done: float,
               rows: list[tuple[Optional[Any], str, int]],
               window: int = 1) -> float:
        """Book one device dispatch.

        ``rows`` is ``[(request_or_None, phase, weight_tokens), ...]``
        — one entry per (slot, phase) share of the dispatch; a fused
        window row typically contributes a ``decode`` entry for its
        consumed tokens and a waste entry for its planned-minus-consumed
        tail. ``window`` is the fused step count K (weight-streaming
        traffic scales with it, not with batch width). Returns the busy
        segment duration in seconds."""
        rows = [(r, ph, int(w)) for r, ph, w in rows if w > 0]
        tok_w = sum(w for _r, _ph, w in rows)
        total_w = tok_w
        if not rows:
            # a dispatch whose every row was dropped (all slots finished
            # mid-flight) still burned chip time — book it as waste so
            # the conservation identity keeps holding
            rows = [(None, "early_exit", 1)]
            total_w = 1
        with self._lock:
            if self._last_complete is None:
                seg_start = t_launch
                self.t_first = t_launch
            else:
                seg_start = max(t_launch, self._last_complete)
                self.idle_ms += max(
                    0.0, (seg_start - self._last_complete)) * 1000.0
            dur = max(0.0, t_done - seg_start)
            if self._last_complete is None or t_done > self._last_complete:
                self._last_complete = t_done
            self.t_last = self._last_complete
            self.dispatches += 1
            self.busy_ms += dur * 1000.0

            for req, phase, w in rows:
                share_ms = (dur * 1000.0 * w / total_w) if total_w else 0.0
                self.phase_ms[phase] += share_ms
                tenant = getattr(req, "tenant", "") or ""
                key = (tenant, phase)
                self.tenant_ms[key] = self.tenant_ms.get(key, 0.0) + share_ms
                if req is not None:
                    req.chip_ms[phase] = req.chip_ms.get(phase, 0.0) + share_ms
                if phase == "decode":
                    self.decode_tokens += w
                elif phase == "prefill":
                    self.prefill_tokens += w

            # planned rows are computed whether or not the stream keeps
            # them — wasted FLOPs are the whole point of measuring
            flops = self.flops_per_token * tok_w
            hbm = (self.param_bytes * max(1, int(window))
                   + self.kv_bytes_per_token * tok_w)
            self.flops += flops
            self.hbm_bytes += hbm
            self._recent.append((t_done, dur, flops, hbm))

            if self.detector is not None and dur > 0.0:
                if self.detector.observe(dur, t_done):
                    self.anomaly_events += 1
                    self._anomaly_pending = True
        return dur

    def reset(self) -> None:
        """Zero all accounting (bench measurement windows exclude warmup
        dispatches this way). The detector's learned baseline survives —
        forgetting it would re-open the warmup window."""
        with self._lock:
            self._last_complete = None
            self.t_first = self.t_last = None
            self.dispatches = 0
            self.busy_ms = self.idle_ms = 0.0
            self.phase_ms = {p: 0.0 for p in PHASES}
            self.tenant_ms = {}
            self.flops = self.hbm_bytes = 0.0
            self.decode_tokens = self.prefill_tokens = 0
            self.anomaly_events = 0
            self._anomaly_pending = False
            self._recent.clear()

    def take_anomaly(self) -> bool:
        """True once per detector trigger (serving-loop poll)."""
        with self._lock:
            pending, self._anomaly_pending = self._anomaly_pending, False
            return pending

    # -- reading (any thread) ------------------------------------------

    def utilization(self, window_s: float = 60.0,
                    now: Optional[float] = None) -> tuple[float, float]:
        """(MFU, MBU) over the trailing ``window_s`` of dispatches."""
        with self._lock:
            if not self._recent:
                return 0.0, 0.0
            t_hi = now if now is not None else self._recent[-1][0]
            lo = t_hi - window_s
            ent = [e for e in self._recent if e[0] >= lo]
            if not ent:
                return 0.0, 0.0
            elapsed = max(t_hi - min(e[0] - e[1] for e in ent), 1e-9)
            mfu = sum(e[2] for e in ent) / (self.peak_flops * elapsed)
            mbu = sum(e[3] for e in ent) / (self.peak_bytes_s * elapsed)
            return min(mfu, 1.0), min(mbu, 1.0)

    def snapshot(self) -> dict:
        """Cumulative totals (ms / counts), for delta-draining into
        metrics and for bench's conservation check."""
        with self._lock:
            attributed = self.phase_ms["prefill"] + self.phase_ms["decode"]
            wasted = sum(self.phase_ms[p] for p in WASTE_PHASES)
            window_ms = ((self.t_last - self.t_first) * 1000.0
                         if self.t_first is not None else 0.0)
            return {
                "phase_ms": dict(self.phase_ms),
                "attributed_ms": attributed,
                "wasted_ms": wasted,
                "idle_ms": self.idle_ms,
                "busy_ms": self.busy_ms,
                "window_ms": window_ms,
                "dispatches": self.dispatches,
                "flops": self.flops,
                "hbm_bytes": self.hbm_bytes,
                "decode_tokens": self.decode_tokens,
                "prefill_tokens": self.prefill_tokens,
                "anomaly_events": self.anomaly_events,
                "tenant_ms": dict(self.tenant_ms),
            }

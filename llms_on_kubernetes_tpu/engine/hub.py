"""Cold-start weight acquisition from the Hugging Face Hub.

The reference's model pods self-download weights on first boot into the PVC
cache (reference vllm-models/helm-chart/templates/model-deployments.yaml:26-47),
authenticated via the optional ``huggingface-token`` Secret exposed as
``HUGGING_FACE_HUB_TOKEN`` (:64-70), with a 7-minute readiness budget to
cover the download (:48-55). This module is the engine-side equivalent:
``ensure_model_dir`` resolves a local checkpoint and, on a miss, downloads
the snapshot (resumable — ``huggingface_hub`` keeps partial files) into the
same ``~/.cache/huggingface`` layout the charts mount as a PVC, then
re-resolves. A download or resolution failure propagates: ``serve`` exits
non-zero and the pod stays unready, exactly the reference's contract.
"""

from __future__ import annotations

import os
from typing import Optional

# Only the files the engine actually reads: sharded safetensors weights,
# the HF config, and tokenizer artifacts. Skipping *.bin/*.pth/*.gguf keeps
# the PVC at the chart's pvcSize for repos that ship multiple formats.
_ALLOW_PATTERNS = (
    "*.safetensors",
    "*.safetensors.index.json",
    "config.json",
    "generation_config.json",
    "tokenizer.json",
    "tokenizer.model",
    "tokenizer_config.json",
    "special_tokens_map.json",
    "vocab.json",
    "merges.txt",
    "added_tokens.json",
    "chat_template.jinja",  # transformers >=4.43 saves the template standalone
    "preprocessor_config.json",  # vision models: image processor settings
)


# A PEFT-format LoRA adapter is two small files; *.bin variants are
# skipped on purpose — the loader only reads safetensors.
_ADAPTER_ALLOW_PATTERNS = (
    "adapter_config.json",
    "adapter_model.safetensors",
)


def hub_token() -> Optional[str]:
    """Token from the mounted Secret: env var or a token file.

    ``HUGGING_FACE_HUB_TOKEN`` is the name the charts set from the
    ``huggingface-token`` Secret (templates/model-deployments.yaml); the
    ``_FILE`` variant supports mounting the Secret as a volume instead."""
    for var in ("HUGGING_FACE_HUB_TOKEN", "HF_TOKEN"):
        tok = os.environ.get(var, "").strip()
        if tok:
            return tok
    path = os.environ.get("HUGGING_FACE_HUB_TOKEN_FILE", "").strip()
    if path and os.path.isfile(path):
        with open(path) as f:
            return f.read().strip() or None
    return None


def download_snapshot(repo_id: str, cache_dir: Optional[str] = None,
                      token: Optional[str] = None) -> str:
    """Download ``repo_id``'s current snapshot into the HF cache; return its dir.

    Resumable: ``snapshot_download`` skips complete files and continues
    partial ones, so a pod restarted mid-download (liveness probe, node
    preemption) picks up where it left off — the PVC is the resume state."""
    from huggingface_hub import snapshot_download

    from llms_on_kubernetes_tpu.engine.weights import hf_hub_cache

    # explicit cache_dir ALWAYS: resolution and download must agree on the
    # snapshot location regardless of which HF_* env vars are set
    return snapshot_download(
        repo_id,
        cache_dir=hf_hub_cache(cache_dir),
        allow_patterns=list(_ALLOW_PATTERNS),
        token=token if token is not None else hub_token(),
    )


def ensure_adapter_dir(adapter_ref: str,
                       cache_dir: Optional[str] = None) -> str:
    """Resolve a local PEFT LoRA adapter dir for ``adapter_ref``,
    downloading the Hub snapshot on a miss (same PVC cache layout as the
    base weights). An explicit directory wins; either way the dir must
    hold ``adapter_config.json`` + ``adapter_model.safetensors`` — an
    incomplete snapshot is a load failure, never a silent base-model
    fallback."""
    if os.path.isdir(adapter_ref):
        path = adapter_ref
    else:
        from huggingface_hub import snapshot_download

        from llms_on_kubernetes_tpu.engine.weights import hf_hub_cache

        path = snapshot_download(
            adapter_ref,
            cache_dir=hf_hub_cache(cache_dir),
            allow_patterns=list(_ADAPTER_ALLOW_PATTERNS),
            token=hub_token(),
        )
    for fname in _ADAPTER_ALLOW_PATTERNS:
        if not os.path.isfile(os.path.join(path, fname)):
            raise FileNotFoundError(
                f"adapter {adapter_ref!r}: {path} has no {fname}")
    return path


def ensure_model_dir(model_ref: str, cache_dir: Optional[str] = None) -> str:
    """Resolve a local checkpoint dir for ``model_ref``, downloading on a miss.

    Resolution order mirrors the reference pod's view of the world:
    an explicit directory wins; then a cached Hub snapshot; then a live
    Hub download (registry names resolve through their canonical repo id).
    Raises ``FileNotFoundError`` when the ref names no repo or the
    download yields no safetensors — callers must treat that as a startup
    failure, never serve random weights implicitly."""
    from llms_on_kubernetes_tpu.configs import hf_repo_for
    from llms_on_kubernetes_tpu.engine.weights import resolve_model_dir

    def grandfathered() -> Optional[str]:
        # A weights-complete but tokenizer-less snapshot (hand-populated
        # PVC, or one written by a pre-tokenizer-check release) still
        # serves — via ByteTokenizer, as it always did — when no resume
        # download can fetch the missing artifacts.
        try:
            path = resolve_model_dir(model_ref, cache_dir=cache_dir,
                                     require_tokenizer=False)
        except FileNotFoundError:
            return None
        import logging
        logging.getLogger(__name__).warning(
            "serving tokenizer-less snapshot %s (no tokenizer artifact on "
            "disk and none could be fetched); requests will use the byte "
            "tokenizer", path)
        return path

    try:
        return resolve_model_dir(model_ref, cache_dir=cache_dir)
    except FileNotFoundError:
        repo_id = hf_repo_for(model_ref)
        if repo_id is None:
            path = grandfathered()
            if path is not None:
                return path
            raise
    try:
        download_snapshot(repo_id, cache_dir=cache_dir)
    except Exception:
        # offline / Hub unreachable / auth failure: fall back to a
        # pre-existing tokenizer-less snapshot before failing startup —
        # but log WHY the download failed first, or a degraded
        # byte-tokenizer deployment leaves no trace of its cause
        import logging
        logging.getLogger(__name__).warning(
            "snapshot download for %s failed", repo_id, exc_info=True)
        path = grandfathered()
        if path is not None:
            return path
        raise
    # re-resolve rather than trusting the returned path: enforces the
    # "snapshot actually contains *.safetensors" invariant in one place
    try:
        return resolve_model_dir(model_ref, cache_dir=cache_dir)
    except FileNotFoundError:
        # download succeeded but the repo itself ships no tokenizer
        # artifact (or allow-patterns missed it): same grandfather rule as
        # the offline path — a weights-complete snapshot still serves
        path = grandfathered()
        if path is not None:
            return path
        raise

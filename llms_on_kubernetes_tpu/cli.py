"""Command-line entry points — the container commands the charts run.

The reference's pods ran `vllm serve <hf-id> --served-model-name <name>
--port 8080 ...` (reference model-deployments.yaml:26-39) and an
OpenResty/Python gateway (model-gateway.yaml / api-gateway.yaml). The
TPU-native equivalents:

    python -m llms_on_kubernetes_tpu serve  --model <ref> --served-model-name <name> [--tp N]
    python -m llms_on_kubernetes_tpu router --backend name=url ... [--strict]
"""

from __future__ import annotations

import argparse
import os
import sys


def _add_serve(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("serve", help="run the OpenAI-compatible engine server")
    p.add_argument("--model", required=True,
                   help="registry name, HF repo id, or checkpoint directory")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--random-weights", action="store_true",
                   help="skip checkpoint loading (benchmarks/smoke tests)")
    p.add_argument("--max-decode-slots", type=int, default=8)
    p.add_argument("--num-pages", type=int, default=2048)
    p.add_argument("--page-size", type=int, default=64)
    p.add_argument("--pages-per-slot", type=int, default=64)
    p.add_argument("--prefill-buckets", default="256,1024,4096")
    p.add_argument("--tensor-parallel-size", "--tp", type=int, default=0,
                   help="0 = all local devices on the mesh 'model' axis")
    p.add_argument("--expert-parallel-size", "--ep", type=int, default=1)
    p.add_argument("--sequence-parallel-size", "--sp", type=int, default=1,
                   help="context-parallel ring size for long prompts "
                        "(prefill runs ring attention over the 'seq' axis)")
    p.add_argument("--quantization", choices=["int8", "fp8", "awq"],
                   default=None,
                   help="int8: weight-only quantize a bf16 checkpoint; "
                        "fp8/awq: assert the checkpoint is that pre-"
                        "quantized format (auto-detected otherwise)")
    p.add_argument("--no-prefix-caching", dest="prefix_caching",
                   action="store_false", default=True,
                   help="disable page-level reuse of shared prompt prefixes")
    p.add_argument("--kv-cache-dtype", choices=["int8"], default=None,
                   help="store KV quantized (halved decode HBM traffic, "
                        "2x token capacity; ~1/127 per-element error)")
    p.add_argument("--kv-host-cache-gb", type=float, default=None,
                   help="host-RAM KV offload tier capacity in GiB: "
                        "finished/preempted sessions park their pages in "
                        "host memory; a returning session re-uploads and "
                        "skips re-prefill (default: $LLMK_KV_HOST_CACHE_GB "
                        "or off; needs prefix caching, single-host only)")
    p.add_argument("--decode-steps", type=int, default=None,
                   help="decode tokens sampled per fused device dispatch "
                        "(default: $LLMK_DECODE_STEPS or 4; forced to 1 "
                        "on multihost)")
    p.add_argument("--speculation", choices=["ngram", "draft"], default=None,
                   help="speculative decoding riding the fused decode "
                        "window: ngram = model-free prompt lookup, draft = "
                        "small draft model via --draft-model (default: "
                        "$LLMK_SPECULATION or off; greedy outputs are "
                        "bit-identical on/off; dropped on multihost)")
    p.add_argument("--draft-model", default=None,
                   help="draft model for --speculation draft (registry "
                        "name or .gguf path; default: $LLMK_DRAFT_MODEL; "
                        "implies --speculation draft)")
    p.add_argument("--no-ledger", dest="ledger", action="store_false",
                   default=None,
                   help="disable the goodput ledger (per-request chip-time "
                        "attribution, MFU/MBU gauges, per-tenant chip-"
                        "seconds; default: $LLMK_LEDGER or on)")
    p.add_argument("--no-anomaly-profile", dest="anomaly_profile",
                   action="store_false", default=None,
                   help="disable the step-time anomaly watchdog's automatic "
                        "profiler captures (default: $LLMK_ANOMALY_PROFILE "
                        "or on)")
    p.add_argument("--anomaly-z", type=float, default=None,
                   help="z-score a dispatch's device time must exceed to "
                        "count as anomalous (default: $LLMK_ANOMALY_Z or "
                        "4.0)")
    p.add_argument("--anomaly-cooldown-s", type=float, default=None,
                   help="minimum seconds between automatic profiler "
                        "captures — the watchdog's rate limit (default: "
                        "$LLMK_ANOMALY_COOLDOWN_S or 600)")
    def _positive_int(v: str) -> int:
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
        return n

    p.add_argument("--max-images-per-request", type=_positive_int, default=4,
                   help="image/frame blocks the mm prefill is compiled for "
                        "(a video counts one block per temporal patch); "
                        "requests beyond it get a 400")
    p.add_argument("--adapter", action="append", default=None,
                   metavar="NAME=REF",
                   help="repeatable: serve LoRA adapter NAME from REF (HF "
                        "repo id or local dir); requests address it as "
                        "model=<served-name>:NAME")
    p.add_argument("--adapter-slots", type=_positive_int, default=4,
                   help="on-device adapter slots (LRU-recycled)")
    p.add_argument("--adapter-rank", type=_positive_int, default=16,
                   help="max LoRA rank the device stacks are sized for")
    p.add_argument("--no-warmup", dest="warmup", action="store_false",
                   default=True,
                   help="skip the pre-serving warmup generation (first "
                        "requests then pay the prefill/decode compiles)")


def configure_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point XLA's persistent compilation cache at a durable directory.

    Cold-start attack: the warmup compiles (20-40 s per executable on a
    real TPU) are the dominant cold-start phase; cached on the weight
    PVC they are paid once per (program, jaxlib, topology), not once per
    pod. Resolution order: ``LLMK_COMPILE_CACHE_DIR`` env (empty string
    DISABLES the cache), explicit ``cache_dir`` arg, else ``xla_cache/``
    next to the HF hub cache — which in the charts lives on the same
    PVC as the weights. Returns the directory used, or None if disabled.
    Must run before the first compilation; call it early in serve.
    """
    import jax

    raw = os.environ.get("LLMK_COMPILE_CACHE_DIR")
    if raw is not None:
        cache_dir = raw.strip() or None
    elif cache_dir is None:
        from llms_on_kubernetes_tpu.engine.weights import hf_hub_cache

        # hf_hub_cache() is <cache-root>/hub; keep XLA artifacts beside
        # it, not inside it (hub tooling owns that layout)
        cache_dir = os.path.join(
            os.path.dirname(hf_hub_cache().rstrip(os.sep)), "xla_cache")
    if not cache_dir:
        return None
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache small programs too: the CPU-side tests (and debug-tiny
    # configs) compile in well under the default 1 s / 4 KiB floors, and
    # a warm restart must hit for them as well. Knob names vary across
    # jax versions — absence just means that floor doesn't exist there.
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):
            pass
    return cache_dir


def _add_router(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("router", help="run the multi-model API gateway")
    p.add_argument("--backend", action="append", default=None,
                   metavar="NAME=URL[|URL...]",
                   help="repeatable: model name=replica url(s), |-separated")
    p.add_argument("--config", default=None,
                   help="router.json (from `render`): backends/default/strict")
    p.add_argument("--default-model", default=None)
    p.add_argument("--strict", action="store_true",
                   help="404 on unknown model instead of silent default fallback")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--probe-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="active /ready probe period per replica "
                        "(default 2.0; 0 disables probing)")
    p.add_argument("--adapters", action="append", default=None,
                   metavar="NAME=ADAPTER[|ADAPTER...]",
                   help="repeatable: LoRA adapters a model's replicas "
                        "serve, addressed as model=NAME:ADAPTER")


def _add_render(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "render",
        help="render K8s manifests from a models[] config (helm-free path)")
    p.add_argument("--config", required=True, help="deploy config YAML")
    p.add_argument("-o", "--output", default="-",
                   help="output file (default: stdout)")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="llms-on-kubernetes-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)
    _add_serve(sub)
    _add_router(sub)
    _add_render(sub)
    args = parser.parse_args(argv)

    if args.cmd == "render":
        from llms_on_kubernetes_tpu.deploy import load_spec, render_manifests, to_yaml

        text = to_yaml(render_manifests(load_spec(args.config)))
        if args.output == "-":
            sys.stdout.write(text)
        else:
            with open(args.output, "w") as f:
                f.write(text)
        return 0

    if args.cmd == "router":
        import json

        from llms_on_kubernetes_tpu.server.router import run_router

        backends = {}
        adapters = {}
        default_model, strict = args.default_model, args.strict
        probe_interval = args.probe_interval
        # None = let Router fall back to the LLMK_STREAM_RESUME /
        # LLMK_RESUME_ATTEMPTS / LLMK_HEDGE_MS env knobs
        stream_resume = resume_attempts = hedge_ms = None
        qos = roles = handoff_retries = None
        # None = let Router fall back to LLMK_OUTLIER / LLMK_RETRY_BUDGET
        # / LLMK_AFFINITY
        outlier_ejection = retry_budget = prefix_affinity = None
        # None = let Router fall back to LLMK_OTLP_ENDPOINT /
        # LLMK_TRACE_SAMPLE / LLMK_SLOW_REQUEST_MS
        tracing_cfg = None
        if args.config:
            with open(args.config) as f:
                cfg = json.load(f)
            backends.update(cfg.get("backends", {}))
            adapters.update(cfg.get("adapters", {}))
            default_model = default_model or cfg.get("default_model")
            strict = strict or bool(cfg.get("strict", False))
            if probe_interval is None and "probe_interval_s" in cfg:
                probe_interval = float(cfg["probe_interval_s"])
            if "stream_resume" in cfg:
                stream_resume = bool(cfg["stream_resume"])
            if "resume_attempts" in cfg:
                resume_attempts = int(cfg["resume_attempts"])
            if "hedge_ms" in cfg:
                hedge_ms = float(cfg["hedge_ms"])
            if "qos" in cfg:
                qos = cfg["qos"]  # per-tenant QoS block, passed verbatim
            if "roles" in cfg:
                # disaggregated serving: replica URL -> prefill|decode|both
                roles = cfg["roles"]
            if "handoff_retries" in cfg:
                handoff_retries = int(cfg["handoff_retries"])
            if "outlier_ejection" in cfg:
                # gray-failure layer: latency/error outlier quarantine,
                # passed verbatim (non-empty block = enabled)
                outlier_ejection = cfg["outlier_ejection"]
            if "retry_budget" in cfg:
                retry_budget = cfg["retry_budget"]
            if "prefix_affinity" in cfg:
                # prefix-affinity + cache-aware routing, passed verbatim
                # (non-empty block = enabled)
                prefix_affinity = cfg["prefix_affinity"]
            if "tracing" in cfg:
                # cross-hop tracing: OTLP export + tail sampling, passed
                # verbatim (non-empty block = exporter enabled)
                tracing_cfg = cfg["tracing"]
        for spec in args.backend or ():
            name, _, urls = spec.partition("=")
            if not urls:
                parser.error(f"--backend must be NAME=URL[|URL...], got {spec!r}")
            backends[name] = [u for u in urls.split("|") if u]
        for spec in args.adapters or ():
            name, _, names = spec.partition("=")
            if not names:
                parser.error(
                    f"--adapters must be NAME=ADAPTER[|ADAPTER...], got {spec!r}")
            adapters[name] = [a for a in names.split("|") if a]
        if not backends:
            parser.error("router needs --config or at least one --backend")
        if probe_interval is None:
            probe_interval = 2.0
        run_router(backends, default_model, strict,
                   host=args.host, port=args.port,
                   probe_interval_s=probe_interval or None,
                   adapters=adapters or None,
                   stream_resume=stream_resume,
                   resume_attempts=resume_attempts, hedge_ms=hedge_ms,
                   qos=qos, roles=roles, handoff_retries=handoff_retries,
                   outlier_ejection=outlier_ejection,
                   retry_budget=retry_budget,
                   prefix_affinity=prefix_affinity,
                   tracing_cfg=tracing_cfg)
        return 0

    # serve
    # Honor an explicit CPU request even when a preloaded sitecustomize
    # already registered a hardware platform plugin (the env var alone is
    # evaluated too late in that case) — same guard as bench.py. Done here,
    # not at the top of main(): render/router must not pay the jax import.
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from llms_on_kubernetes_tpu.parallel.distributed import maybe_initialize
    from llms_on_kubernetes_tpu.server.metrics import cold_start

    with cold_start.phase("mesh"):
        multi_host = maybe_initialize()  # join pod group BEFORE backend init

    import jax

    # before any compilation: warm restarts reuse cached executables
    cache_dir = configure_compilation_cache()
    if cache_dir:
        print(f"[serve] persistent compile cache: {cache_dir}",
              file=sys.stderr)

    from llms_on_kubernetes_tpu.configs import from_hf_config, get_config
    from llms_on_kubernetes_tpu.engine.engine import Engine, EngineConfig
    from llms_on_kubernetes_tpu.engine.tokenizer import load_tokenizer
    from llms_on_kubernetes_tpu.parallel.mesh import make_mesh
    from llms_on_kubernetes_tpu.server.openai_api import run_server

    model_dir = None
    model_cfg = None
    gguf_path = None
    # GGUF file path: the local solution's `modelPath` contract (reference
    # ramalama values.yaml modelPath -> llama-server --model <file>.gguf)
    gguf_file = None
    if args.model.endswith(".gguf"):
        if not os.path.isfile(args.model):
            raise SystemExit(f"GGUF file not found: {args.model}")
        gguf_path = args.model
        from llms_on_kubernetes_tpu.engine.gguf import GGUFFile, config_from_gguf

        # parsed ONCE; reused for config, weights, and the embedded tokenizer
        gguf_file = GGUFFile(gguf_path)
        model_cfg = config_from_gguf(gguf_file, name=args.served_model_name)
    else:
        try:
            model_cfg = get_config(args.model)
        except KeyError:
            pass
        if not args.random_weights:
            # Missing weights are a STARTUP FAILURE: exit non-zero so the
            # pod stays unready (the reference's 7-min readiness budget
            # exists exactly for the first-boot download, reference
            # model-deployments.yaml:26-70). Random weights only ever
            # behind the explicit --random-weights flag.
            from llms_on_kubernetes_tpu.engine.hub import ensure_model_dir

            try:
                model_dir = ensure_model_dir(args.model)
            except Exception as e:
                # FileNotFoundError/OSError cover the expected operational
                # failures (no checkpoint, unmounted PVC, Hub HTTP/auth
                # errors — requests' exceptions subclass OSError); anything
                # else is a bug, so keep its traceback in the pod log.
                if not isinstance(e, OSError):
                    import traceback
                    traceback.print_exc()
                raise SystemExit(
                    f"[serve] cannot obtain weights for {args.model!r}: {e}\n"
                    f"[serve] (pass --random-weights explicitly to serve an "
                    f"uninitialized model for smoke tests/benchmarks)"
                )
        if model_cfg is None and model_dir is not None:
            cfg_path = os.path.join(model_dir, "config.json")
            model_cfg = from_hf_config(cfg_path, name=args.model)
    if model_cfg is None:
        raise SystemExit(f"cannot resolve model {args.model!r}")

    # cold-start attack: open/mmap the checkpoint shards (pure host I/O)
    # in the background WHILE the device mesh is built below
    weights_preload = None
    if model_dir is not None and not args.random_weights:
        from llms_on_kubernetes_tpu.engine.weights import WeightsPreload

        weights_preload = WeightsPreload(model_dir)

    n_dev = len(jax.devices())
    ep = args.expert_parallel_size
    sp = args.sequence_parallel_size
    if ep < 1 or sp < 1 or n_dev % (ep * sp) != 0:
        parser.error(f"--ep {ep} x --sp {sp} must divide the local "
                     f"device count ({n_dev})")
    tp = args.tensor_parallel_size or n_dev // (ep * sp)
    if tp < 1 or ep * sp * tp > n_dev:
        parser.error(f"--tp {tp} x --ep {ep} x --sp {sp} exceeds the "
                     f"{n_dev} local devices")
    with cold_start.phase("mesh"):
        mesh = make_mesh(data=1, seq=sp, expert=ep, model=tp)

    adapters = {}
    for spec in args.adapter or ():
        name, _, ref = spec.partition("=")
        if not name or not ref:
            parser.error(f"--adapter must be NAME=REF, got {spec!r}")
        adapters[name] = ref

    # LLMK_QOS: engine-side fair-queue config as JSON, e.g.
    # {"weights": {"alice": 4}, "priorities": {"bulk": "batch"},
    #  "default_priority": "normal", "starvation_s": 5} — env (not a flag)
    # so the chart can feed one ConfigMap value to every model pod
    qos_kw = {}
    raw_qos = os.environ.get("LLMK_QOS", "").strip()
    if raw_qos:
        import json

        try:
            q = json.loads(raw_qos)
        except ValueError as e:
            raise SystemExit(f"[serve] LLMK_QOS is not valid JSON: {e}")
        qos_kw = dict(
            qos_weights=q.get("weights", ()),
            qos_priorities=q.get("priorities", ()),
            qos_default_weight=float(q.get("default_weight", 1.0)),
            qos_default_priority=str(q.get("default_priority", "normal")),
            qos_starvation_s=float(q.get("starvation_s", 5.0)),
        )

    engine_cfg = EngineConfig(
        model=model_cfg.name,
        dtype=args.dtype,
        max_decode_slots=args.max_decode_slots,
        num_pages=args.num_pages,
        page_size=args.page_size,
        pages_per_slot=args.pages_per_slot,
        prefill_buckets=tuple(int(x) for x in args.prefill_buckets.split(",")),
        quantization=args.quantization,
        prefix_caching=args.prefix_caching,
        kv_cache_dtype=args.kv_cache_dtype,
        kv_host_cache_gb=args.kv_host_cache_gb,
        decode_steps=args.decode_steps,
        speculation=args.speculation,
        draft_model=args.draft_model,
        ledger=args.ledger,
        anomaly_profile=args.anomaly_profile,
        anomaly_z=args.anomaly_z,
        anomaly_cooldown_s=args.anomaly_cooldown_s,
        max_images_per_request=args.max_images_per_request,
        adapters=adapters,
        adapter_slots=args.adapter_slots,
        adapter_rank=args.adapter_rank,
        # only the coordinator schedules; its engine broadcasts step inputs
        multihost=multi_host,
        **qos_kw,
    )
    gguf_params = None
    if gguf_file is not None and not args.random_weights:
        from llms_on_kubernetes_tpu.engine.gguf import load_gguf_params

        with cold_start.phase("load"):
            _, gguf_params = load_gguf_params(
                gguf_file, cfg=model_cfg, dtype=args.dtype,
                quantization=args.quantization, mesh=mesh,
            )  # closes the mmap; the parsed metadata dict stays usable
    elif gguf_file is not None:
        gguf_file.close()
    with cold_start.phase("load"):
        engine = Engine(engine_cfg, model_config=model_cfg, mesh=mesh,
                        params=gguf_params,
                        model_dir=None if (args.random_weights
                                           or gguf_params is not None)
                        else model_dir,
                        weights_preload=weights_preload)
    if gguf_file is not None:
        # prefer HF tokenizer files beside the .gguf; else the tokenizer
        # embedded in the GGUF metadata itself (a bare .gguf is the
        # documented modelPath contract — it carries its own vocab)
        from llms_on_kubernetes_tpu.engine.tokenizer import (
            ByteTokenizer, GGUFTokenizer,
        )

        tokenizer = load_tokenizer(os.path.dirname(gguf_path) or ".")
        if (isinstance(tokenizer, ByteTokenizer)
                and "tokenizer.ggml.tokens" in gguf_file.metadata):
            tokenizer = GGUFTokenizer(gguf_file.metadata)
    else:
        tokenizer = load_tokenizer(model_dir)
    served = args.served_model_name or model_cfg.name
    print(f"[serve] {served}: mesh={dict(mesh.shape)} dtype={args.dtype} "
          f"max_len={engine_cfg.max_model_len} multi_host={multi_host}",
          file=sys.stderr)
    if multi_host:
        from llms_on_kubernetes_tpu.engine.multihost import follower_loop
        from llms_on_kubernetes_tpu.parallel.distributed import is_coordinator

        if not is_coordinator():
            # followers never serve HTTP: they mirror the coordinator's
            # broadcast step sequence so every process enters the same
            # SPMD programs (engine/multihost.py)
            print("[serve] follower pod: entering SPMD mirror loop",
                  file=sys.stderr)
            follower_loop(engine)
            return 0
    try:
        if args.warmup:
            # build the prefill + decode executables BEFORE taking
            # traffic (or fetch them from the persistent cache): the
            # first real request must not pay a 20-40 s compile. Timed
            # as the "compile" cold-start phase.
            from llms_on_kubernetes_tpu.engine.engine import SamplingParams

            with cold_start.phase("compile"):
                w = engine.submit(
                    [1, 2, 3, 4],
                    SamplingParams(temperature=0.0, max_tokens=2))
                while not w.finished:
                    engine.step()
            print("[serve] warmup complete", file=sys.stderr)
        run_server(engine, tokenizer, served, host=args.host, port=args.port)
    finally:
        engine.stop_followers()  # release follower pods' mirror loops
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

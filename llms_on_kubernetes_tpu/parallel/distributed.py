"""Multi-host process-group bring-up on Kubernetes.

The reference's distributed story was NCCL inside one pod (SURVEY §2.4);
multi-host serving did not exist. Here a model larger than one TPU host
(e.g. Llama-3-70B on v5p-16) runs as a StatefulSet pod group (see
deploy/manifests.py::render_model_multi_host): every pod runs the same
engine binary, and this module turns the pod group into one JAX process
group — after ``jax.distributed.initialize``, ``jax.devices()`` spans the
whole slice and the SPMD partitioner emits ICI collectives across hosts.

Contract with the manifests (env injected there):
  JAX_COORDINATOR_ADDRESS  host:port of pod 0 (stable headless-Service DNS)
  JAX_NUM_PROCESSES        number of slice hosts
  POD_NAME                 ``<statefulset>-<ordinal>`` — ordinal = process id
"""

from __future__ import annotations

import os
import re
from typing import Optional


def pod_ordinal(pod_name: str) -> int:
    """StatefulSet pod name -> stable process index (``model-x-3`` -> 3)."""
    m = re.search(r"-(\d+)$", pod_name)
    if not m:
        raise ValueError(
            f"POD_NAME {pod_name!r} has no trailing ordinal; multi-host "
            f"serving requires StatefulSet-style pod names"
        )
    return int(m.group(1))


def distributed_env() -> Optional[dict]:
    """Parse the K8s multi-host env contract; None = single-host mode."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = int(os.environ.get("JAX_NUM_PROCESSES", "1") or "1")
    if not addr or n <= 1:
        return None
    pid_env = os.environ.get("JAX_PROCESS_ID")
    if pid_env is not None:
        pid = int(pid_env)
    else:
        pid = pod_ordinal(os.environ.get("POD_NAME", ""))
    if not (0 <= pid < n):
        raise ValueError(f"process id {pid} out of range for {n} processes")
    return {"coordinator_address": addr, "num_processes": n, "process_id": pid}


def maybe_initialize() -> bool:
    """Join the pod group's JAX process group if the env says we're one of
    a multi-host set. Returns True when distributed mode is active.

    Idempotent: safe to call from both the CLI and library entry points.
    """
    env = distributed_env()
    if env is None:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=env["coordinator_address"],
        num_processes=env["num_processes"],
        process_id=env["process_id"],
    )
    return True


def is_coordinator() -> bool:
    """Pod 0 serves HTTP; followers execute the same SPMD programs."""
    env = distributed_env()
    return env is None or env["process_id"] == 0

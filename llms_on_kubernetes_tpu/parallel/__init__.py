from llms_on_kubernetes_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    make_mesh,
)
from llms_on_kubernetes_tpu.parallel.sharding import (
    cache_specs,
    param_specs,
    shard_params,
)

__all__ = [
    "AXIS_DATA", "AXIS_EXPERT", "AXIS_MODEL",
    "make_mesh", "param_specs", "cache_specs", "shard_params",
]

"""Sharding rules: parameter / cache PartitionSpecs for the decoder.

Megatron-style tensor parallelism expressed declaratively: column-parallel
q/k/v and gate/up (output head / hidden axis over "model"), row-parallel
wo/w_down (input axis over "model" — XLA inserts the psum), vocab-parallel
embedding and lm_head. MoE expert weights additionally shard their expert
axis over "expert". The paged KV pool shards its kv-head axis over "model"
so each chip's pages hold only its own heads.

When a dimension doesn't divide the axis size (e.g. 4 kv heads on an
8-way model axis), the rule degrades to replication for that tensor —
same behaviour serving engines use for small-GQA models.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llms_on_kubernetes_tpu.configs import ModelConfig
from llms_on_kubernetes_tpu.parallel.mesh import AXIS_DATA, AXIS_EXPERT, AXIS_MODEL

Params = dict[str, Any]


def _axis(mesh: Mesh, dim: int, axis: str):
    """Use `axis` for this dim if it divides evenly, else replicate."""
    size = mesh.shape[axis]
    return axis if size > 1 and dim % size == 0 else None


def param_specs(cfg: ModelConfig, mesh: Mesh) -> Params:
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    m_h = _axis(mesh, H, AXIS_MODEL)
    m_kv = _axis(mesh, KV, AXIS_MODEL)
    m_f = _axis(mesh, F, AXIS_MODEL)
    m_v = _axis(mesh, V, AXIS_MODEL)

    layers: Params = {
        "attn_norm": P(),
        "wq": P(None, None, m_h, None),
        "wk": P(None, None, m_kv, None),
        "wv": P(None, None, m_kv, None),
        "wo": P(None, m_h, None, None),
        "mlp_norm": P(),
    }
    if cfg.attention_bias:
        layers["bq"] = P(None, m_h, None)
        layers["bk"] = P(None, m_kv, None)
        layers["bv"] = P(None, m_kv, None)
    if cfg.qk_norm:
        layers["q_norm"] = P()
        layers["k_norm"] = P()
    if cfg.post_norms:
        layers["attn_post_norm"] = P()
        layers["mlp_post_norm"] = P()
    if cfg.is_moe:
        e = _axis(mesh, cfg.num_experts, AXIS_EXPERT)
        layers["router"] = P()
        layers["w_gate"] = P(None, e, None, m_f)
        layers["w_up"] = P(None, e, None, m_f)
        layers["w_down"] = P(None, e, m_f, None)
    else:
        layers["w_gate"] = P(None, None, m_f)
        layers["w_up"] = P(None, None, m_f)
        layers["w_down"] = P(None, m_f, None)

    specs: Params = {
        "embed": P(m_v, None),
        "final_norm": P(),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, m_v)
    return specs


def cache_specs(cfg: ModelConfig, mesh: Mesh) -> tuple[P, P]:
    from llms_on_kubernetes_tpu.parallel.mesh import AXIS_SEQ

    m_kv = _axis(mesh, cfg.num_kv_heads, AXIS_MODEL)
    sq = AXIS_SEQ if mesh.shape.get(AXIS_SEQ, 1) > 1 else None
    spec = P(m_kv, sq, None, None)  # [KV, L*P, page, hd] flat head-major
    return spec, spec


def shard_pool(pool, cfg: ModelConfig, mesh: Mesh):
    """Device_put a KVPool onto the mesh: every leaf (int8 data AND the
    per-token scales) shards its leading kv-head axis over the model axis,
    so each TP shard keeps its own heads' pages and scales local. On a
    seq>1 mesh the FLAT PAGE axis additionally shards over ``seq``
    (context parallelism, ops/cp.py): total KV capacity then scales with
    the ring size instead of being bounded by one device's share."""
    from llms_on_kubernetes_tpu.parallel.mesh import AXIS_SEQ

    m_kv = _axis(mesh, cfg.num_kv_heads, AXIS_MODEL)
    sq = AXIS_SEQ if mesh.shape.get(AXIS_SEQ, 1) > 1 else None

    def put(x):
        spec = P(m_kv, sq, *([None] * (x.ndim - 2)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, pool)


def shard_lora_stack(stack, mesh: Mesh):
    """Device_put a LoRAStack onto the mesh, rank-parallel when possible.

    The rank dimension is the stack's only axis guaranteed shardable
    regardless of the base model's head/hidden divisibility (the engine
    picks it), and it is a contraction of the delta — so each device holds
    a rank shard of BOTH factors and ``lora_delta`` psums the partial
    deltas (mirrors row-parallel wo/w_down). When the model axis doesn't
    divide the rank, the stack replicates (it's tiny: 2*S*r*(in+out))."""
    from llms_on_kubernetes_tpu.ops.lora import LoRAStack

    ax = _axis(mesh, stack.rank, AXIS_MODEL)
    a_spec = P(*([None] * (stack.a.ndim - 1)), ax)      # [..., S, *in, r]
    b_spec = P(None, None, ax,                          # [L, S, r, *out]
               *([None] * (stack.b.ndim - 3)))
    return LoRAStack(
        jax.device_put(stack.a, NamedSharding(mesh, a_spec)),
        jax.device_put(stack.b, NamedSharding(mesh, b_spec)),
        rank_axis=ax,
    )


def batch_spec() -> P:
    return P(AXIS_DATA)


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Device_put params onto the mesh according to param_specs.

    Int8-quantized weights (``QTensor``) shard their data with the weight's
    spec and their (keepdims) scale with the same axes on the non-reduced
    dims — so a TP-sharded weight carries its channel scales on the same
    chip as the channels.
    """
    from llms_on_kubernetes_tpu.ops.quant import GroupQTensor, QTensor, scale_spec

    specs = param_specs(cfg, mesh)
    if "vision" in params:
        # the vision tower is small relative to the decoder: replicate
        specs["vision"] = jax.tree.map(lambda _: P(), params["vision"])

    def put(x, s):
        if isinstance(x, GroupQTensor):
            # group-quantized (AWQ-native) weight: shard the FLAT OUTPUT
            # axis with the model axis the original spec put on any of
            # the logical out dims (column-parallel preserved). When the
            # original spec is CONTRACTION-sharded instead (row-parallel
            # wo/w_down), shard the GROUP axis over that mesh axis —
            # group_qeinsum partial-sums the local groups and psums
            # (group_axis below) — so TP actually divides the per-device
            # weight bytes for those tensors instead of replicating.
            k = len(x.out_shape)
            out_axes = tuple(s)[-k:] if len(tuple(s)) >= k else ()
            m = next((a for a in out_axes if a is not None), None)
            if m is not None and x.data.shape[-1] % mesh.shape[m] != 0:
                m = None
            if m is not None:
                def spec_for(arr):
                    return P(*([None] * (arr.ndim - 1)), m)
                return GroupQTensor(
                    jax.device_put(x.data,
                                   NamedSharding(mesh, spec_for(x.data))),
                    jax.device_put(x.scale,
                                   NamedSharding(mesh, spec_for(x.scale))),
                    jax.device_put(
                        x.zero_scaled,
                        NamedSharding(mesh, spec_for(x.zero_scaled))),
                    x.out_shape, packed=x.packed)
            # row-parallel: the contraction part of the original spec
            # (between the layer-stack dim and the out dims) names the
            # mesh axis; the G axis must divide it.
            con = tuple(s)[1:len(tuple(s)) - k]
            ax = next((a for a in reversed(con) if a is not None), None)
            G = x.data.shape[-3]
            if ax is not None and mesh.shape[ax] > 1 \
                    and G % mesh.shape[ax] == 0:
                def gspec(arr, tail):  # shard the G axis (ndim - tail - 1)
                    return P(*([None] * (arr.ndim - 1 - tail)), ax,
                             *([None] * tail))
                return GroupQTensor(
                    jax.device_put(
                        x.data, NamedSharding(mesh, gspec(x.data, 2))),
                    jax.device_put(
                        x.scale, NamedSharding(mesh, gspec(x.scale, 1))),
                    jax.device_put(
                        x.zero_scaled,
                        NamedSharding(mesh, gspec(x.zero_scaled, 1))),
                    x.out_shape, packed=x.packed, group_axis=ax)
            # no shardable axis: replicate (degenerate-mesh fallback)
            return GroupQTensor(
                jax.device_put(x.data, NamedSharding(mesh, P())),
                jax.device_put(x.scale, NamedSharding(mesh, P())),
                jax.device_put(x.zero_scaled, NamedSharding(mesh, P())),
                x.out_shape, packed=x.packed)
        if isinstance(x, QTensor):
            data = jax.device_put(x.data, NamedSharding(mesh, s))
            scale = jax.device_put(
                x.scale, NamedSharding(mesh, scale_spec(s, x.scale.shape))
            )
            return QTensor(data, scale)
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree.map(
        put, params, specs,
        is_leaf=lambda x: isinstance(x, (QTensor, GroupQTensor))
    )

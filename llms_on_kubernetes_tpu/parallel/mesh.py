"""Device mesh construction.

The reference expressed intra-model parallelism as a single vLLM flag
(``--tensor-parallel-size``, backed by NCCL — reference
vllm-models/helm-chart/templates/model-deployments.yaml:37-38). Here the
equivalent surface is a ``jax.sharding.Mesh`` over the slice's chips with
three logical axes:

- ``model``  — tensor parallelism (attention heads / FFN hidden), collectives
  ride ICI; the `tensor-parallel-size` analogue.
- ``expert`` — expert parallelism for MoE (Mixtral), all-to-alls over ICI.
- ``data``   — within-engine batch parallelism (request-level DP remains K8s
  `replicas`, exactly as the reference did).

On a multi-host slice the same mesh spans all processes'
``jax.devices()`` — XLA's SPMD partitioner emits ICI/DCN collectives, no
NCCL/MPI (see parallel/distributed.py for process-group bring-up on K8s).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_SEQ = "seq"        # context/sequence parallelism (ring attention)
AXIS_EXPERT = "expert"
AXIS_MODEL = "model"
MESH_AXES = (AXIS_DATA, AXIS_SEQ, AXIS_EXPERT, AXIS_MODEL)


def make_mesh(
    data: int = 1,
    seq: int = 1,
    expert: int = 1,
    model: int = -1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, seq, expert, model) mesh.

    ``model=-1`` absorbs all remaining devices (the common serving case:
    one engine = one slice, fully tensor-parallel). ``seq`` is the ring
    axis for long-context attention (ops/ring_attention.py); placing it
    outermost-but-one keeps ring neighbours physically adjacent on the ICI
    torus within each data replica.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model == -1:
        if n % (data * seq * expert) != 0:
            raise ValueError(
                f"{n} devices not divisible by data*seq*expert="
                f"{data * seq * expert}")
        model = n // (data * seq * expert)
    need = data * seq * expert * model
    if need > n:
        raise ValueError(
            f"mesh {data}x{seq}x{expert}x{model} needs {need} devices, have {n}")
    arr = np.asarray(devices[:need]).reshape(data, seq, expert, model)
    return Mesh(arr, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    d = device or jax.devices()[0]
    return Mesh(np.asarray([d]).reshape(1, 1, 1, 1), MESH_AXES)


# ---------------------------------------------------------------------------
# Active-mesh context: how ops learn the engine's mesh at TRACE time.
# The decoder's functional forward passes take no mesh argument (jit
# signature stability); ops that need collective context — the ring
# attention dispatch for seq>1 meshes — read it here instead. Set once by
# the Engine at construction; trace-time-only state, never read inside a
# compiled computation.
# ---------------------------------------------------------------------------

_ACTIVE_MESH: Optional[Mesh] = None


def set_active_mesh(mesh: Optional[Mesh]) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def get_active_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def seq_parallelism() -> int:
    """Size of the active mesh's seq (context-parallel) axis, or 1."""
    m = _ACTIVE_MESH
    if m is None or AXIS_SEQ not in m.shape:
        return 1
    return int(m.shape[AXIS_SEQ])

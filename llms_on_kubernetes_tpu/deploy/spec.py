"""The ``models[]`` configuration contract, validated.

Extends the reference's schema (reference vllm-models/helm-chart/
values.yaml:1-27: ``huggingfaceId, modelName, gpuRequestCount, replicas,
pvcSize``) the way SURVEY §7.6 prescribes: TPU topology instead of a GPU
count, explicit sharding (tp/ep/dp), per-model engine-arg passthrough (the
reference hardcoded engine flags in its template — SURVEY §5 "Config"), and
schema validation with actionable errors (the reference had none; its dead
``dnsResolver`` value shipped unnoticed, values.yaml:36-39).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,51}[a-z0-9])?$")

# Prometheus the KEDA ScaledObject triggers query (scrapes the router's
# /metrics/cluster); the kube-prometheus-stack default in-cluster address.
DEFAULT_PROMETHEUS_URL = \
    "http://prometheus-server.monitoring.svc.cluster.local:9090"

# chips per host for each accelerator type: a request larger than this
# renders a multi-host slice (LeaderWorkerSet-style pod group).
CHIPS_PER_HOST = {"v5e": 8, "v5p": 4, "v6e": 8}
VALID_TOPOLOGIES = {
    "v5e": {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8", 64: "8x8", 256: "16x16"},
    "v5p": {4: "2x2x1", 8: "2x2x2", 16: "2x2x4", 32: "2x4x4", 64: "4x4x4"},
    "v6e": {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8", 64: "8x8", 256: "16x16"},
}


class SpecError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class TPUSpec:
    accelerator: str = "v5e"     # v5e | v5p | v6e
    chips: int = 8
    topology: Optional[str] = None  # derived from chips if omitted
    # explicit pod-group size; None => derived from chips / chips-per-host
    hosts_override: Optional[int] = None

    def resolved_topology(self) -> str:
        if self.topology:
            return self.topology
        table = VALID_TOPOLOGIES[self.accelerator]
        if self.chips not in table:
            raise SpecError(
                f"no default topology for {self.chips} {self.accelerator} chips; "
                f"known: {sorted(table)} (or set tpu.topology explicitly)"
            )
        return table[self.chips]

    @property
    def hosts(self) -> int:
        if self.hosts_override is not None:
            return self.hosts_override
        per = CHIPS_PER_HOST[self.accelerator]
        return max(1, -(-self.chips // per))

    @property
    def chips_per_host(self) -> int:
        # chips % hosts == 0 is enforced at load time (_tpu_from), so this
        # equals the Helm chart's chipsPerHost helper (exact division) and
        # per-pod requests always sum to tpu.chips
        return max(1, self.chips // self.hosts)

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1

    @property
    def gke_accelerator(self) -> str:
        return {
            "v5e": "tpu-v5-lite-podslice",
            "v5p": "tpu-v5p-slice",
            "v6e": "tpu-v6e-slice",
        }[self.accelerator]


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    tp: int = 0        # 0 => all chips on the tensor axis
    ep: int = 1
    data: int = 1

    def resolve(self, chips: int) -> "ShardingSpec":
        tp = self.tp or chips // (self.ep * self.data)
        if tp * self.ep * self.data != chips:
            raise SpecError(
                f"sharding tp={tp} x ep={self.ep} x data={self.data} != "
                f"{chips} chips"
            )
        return ShardingSpec(tp=tp, ep=self.ep, data=self.data)


@dataclasses.dataclass(frozen=True)
class AutoscalingSpec:
    """Per-model closed-loop autoscaling knobs (``autoscaling:`` block).

    ``min_replicas >= 1`` renders an ``autoscaling/v2`` HPA scaling on
    ``llm_queue_depth`` (served per-pod by prometheus-adapter) plus the
    router's TTFT-SLO attainment (``llm_slo_ttft_miss_ratio`` as an
    Object metric on the api-gateway Service). ``min_replicas == 0``
    renders a KEDA ScaledObject instead — the HPA cannot scale to zero —
    whose prometheus triggers query the same series Prometheus scrapes
    from the router's ``/metrics/cluster``.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    # waiting requests per replica before adding one (llm_queue_depth)
    queue_depth_target: int = 8
    # scale out when the TTFT-SLO ok ratio drops below this attainment
    ttft_ok_ratio_floor: float = 0.95

    def validate(self, model_name: str) -> None:
        if self.min_replicas < 0:
            raise SpecError(
                f"model {model_name}: autoscaling.minReplicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise SpecError(
                f"model {model_name}: autoscaling.maxReplicas="
                f"{self.max_replicas} must be >= max(1, minReplicas="
                f"{self.min_replicas})")
        if self.queue_depth_target < 1:
            raise SpecError(
                f"model {model_name}: autoscaling.queueDepthTarget must "
                f"be >= 1")
        if not (0.0 < self.ttft_ok_ratio_floor <= 1.0):
            raise SpecError(
                f"model {model_name}: autoscaling.ttftOkRatioFloor must be "
                f"in (0, 1], got {self.ttft_ok_ratio_floor}")


@dataclasses.dataclass(frozen=True)
class AnomalyProfileSpec:
    """Step-time anomaly watchdog knobs (``anomalyProfile:`` block).

    The goodput ledger feeds every dispatch's device time to an EWMA +
    z-score detector; a sustained anomaly triggers ONE bounded profiler
    capture (``llm_auto_profile_total{reason="step_anomaly"}``),
    rate-limited by ``cooldownS``. Rendered as LLMK_ANOMALY_* env vars.
    """

    enabled: bool = True
    threshold: float = 4.0      # z-score a dispatch must exceed
    cooldown_s: float = 600.0   # min seconds between automatic captures

    def validate(self, model_name: str) -> None:
        if self.threshold <= 0:
            raise SpecError(
                f"model {model_name}: anomalyProfile.threshold must be "
                f"> 0, got {self.threshold}")
        if self.cooldown_s < 0:
            raise SpecError(
                f"model {model_name}: anomalyProfile.cooldownS must be "
                f">= 0, got {self.cooldown_s}")


_QOS_PRIORITIES = ("interactive", "normal", "batch")


@dataclasses.dataclass(frozen=True)
class TenantQoSSpec:
    """One tenant's QoS contract (an entry under ``qos.tenants``, or the
    ``qos.default`` applied to unlisted tenants). Keys are the router.json
    wire names — the block is passed to both routers verbatim."""

    weight: float = 1.0            # DRR share inside its priority class
    priority: Optional[str] = None  # interactive | normal | batch
    rps: float = 0.0               # requests/s bucket; 0 = unlimited
    burst: float = 0.0             # rps bucket capacity; 0 = derived
    tokens_per_min: float = 0.0    # generated-token budget; 0 = unlimited

    def validate(self, label: str) -> None:
        if self.priority is not None and self.priority not in _QOS_PRIORITIES:
            raise SpecError(
                f"qos {label}: priority must be one of {_QOS_PRIORITIES}, "
                f"got {self.priority!r}")
        if self.weight <= 0:
            raise SpecError(f"qos {label}: weight must be > 0")
        for k in ("rps", "burst", "tokens_per_min"):
            if getattr(self, k) < 0:
                raise SpecError(f"qos {label}: {k} must be >= 0")

    def to_wire(self) -> dict:
        out: dict = {}
        if self.weight != 1.0:
            out["weight"] = self.weight
        if self.priority is not None:
            out["priority"] = self.priority
        for k in ("rps", "burst", "tokens_per_min"):
            v = getattr(self, k)
            if v:
                out[k] = v
        return out


@dataclasses.dataclass(frozen=True)
class QoSSpec:
    """The gateway-level QoS block (``qos:``): per-tenant weighted fair
    shares + token-bucket rate limits, and the adaptive brownout ladder.
    Rendered verbatim into router.json — the python and native routers
    parse identical keys (tests/data/qos_vectors.json is the semantics
    contract between them)."""

    tenants: tuple[tuple[str, TenantQoSSpec], ...] = ()
    default: Optional[TenantQoSSpec] = None
    # brownout signals: 0 disables a signal entirely
    has_brownout: bool = False
    queue_depth_hi: float = 0.0
    burn_rate_hi: float = 0.0
    clamp_max_tokens: int = 64
    # the values.yaml block as given; to_wire() emits it verbatim so the
    # Python renderer and the Go template (`toJson .Values.qos`) produce
    # byte-identical router.json qos blocks (field-level parity tests)
    raw: Optional[dict] = None

    def validate(self) -> None:
        for name, t in self.tenants:
            if not name:
                raise SpecError("qos.tenants: tenant names must be "
                                "non-empty strings")
            t.validate(f"tenant {name!r}")
        if self.default is not None:
            self.default.validate("default")
        if self.queue_depth_hi < 0 or self.burn_rate_hi < 0:
            raise SpecError("qos.brownout thresholds must be >= 0")
        if self.clamp_max_tokens < 1:
            raise SpecError("qos.brownout.clamp_max_tokens must be >= 1")

    def to_wire(self) -> dict:
        if self.raw is not None:
            return self.raw  # callers serialize, never mutate
        out: dict = {}
        if self.tenants:
            out["tenants"] = {n: t.to_wire() for n, t in self.tenants}
        if self.default is not None:
            out["default"] = self.default.to_wire()
        if self.has_brownout:
            b: dict = {}
            if self.queue_depth_hi:
                b["queue_depth_hi"] = self.queue_depth_hi
            if self.burn_rate_hi:
                b["burn_rate_hi"] = self.burn_rate_hi
            if self.clamp_max_tokens != 64:
                b["clamp_max_tokens"] = self.clamp_max_tokens
            out["brownout"] = b
        return out


# gray-failure layer (ISSUE 17): knob names are the router.json wire keys
# — server/outlier.py is the executable spec, tests/data/outlier_vectors.json
# pins both routers to identical semantics
_OUTLIER_KEYS = frozenset({
    "ewma_alpha", "z_threshold", "cv_floor", "err_spread_floor",
    "min_ttft_ms", "err_floor", "min_samples", "streak",
    "max_eject_fraction", "shadow_every", "readmit_successes",
})
_RETRY_BUDGET_KEYS = frozenset({"ratio", "min_per_s", "burst"})
# prefix-affinity + cache-aware routing (ISSUE 18): wire keys of the
# router.json "prefix_affinity" block — server/affinity.py is the
# executable spec, tests/data/affinity_vectors.json pins both routers
_AFFINITY_KEYS = frozenset({
    "enabled", "prefix_chars", "filter_bits", "filter_hashes",
    "overload_factor", "overload_slack", "key_cache", "max_digests",
    "kv_fetch",
})
_AFFINITY_BOOL_KEYS = frozenset({"enabled", "kv_fetch"})

# router.json "tracing" block + engine env — server/tracing.py is the
# executable spec, tests/data/trace_vectors.json pins both routers
_TRACING_KEYS = frozenset({"otlpEndpoint", "sample", "tailSlowMs"})


@dataclasses.dataclass(frozen=True)
class OutlierEjectionSpec:
    """Latency/error outlier ejection config (``outlierEjection:``): the
    gray-failure detector that quarantines a replica whose in-band TTFT or
    error EWMA is a z-score outlier vs same-model-same-role peers while
    its probes stay green. Rendered verbatim into router.json — a
    non-empty block enables the layer in both routers."""

    # the values.yaml block as given; to_wire() emits it verbatim so the
    # Python renderer and the Go template (`toJson .Values.outlierEjection`)
    # produce byte-identical router.json blocks
    raw: dict = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        unknown = set(self.raw) - _OUTLIER_KEYS
        if unknown:
            raise SpecError(
                f"unknown outlierEjection keys: {sorted(unknown)} "
                f"(known: {sorted(_OUTLIER_KEYS)})")
        for k, v in self.raw.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise SpecError(
                    f"outlierEjection.{k} must be a number, got {v!r}")
            if v < 0:
                raise SpecError(
                    f"outlierEjection.{k} must be >= 0, got {v}")
        alpha = self.raw.get("ewma_alpha")
        if alpha is not None and not (0 < alpha <= 1):
            raise SpecError(
                f"outlierEjection.ewma_alpha must be in (0, 1], got {alpha}")
        frac = self.raw.get("max_eject_fraction")
        if frac is not None and frac > 1:
            raise SpecError(
                f"outlierEjection.max_eject_fraction must be <= 1, "
                f"got {frac}")

    def to_wire(self) -> dict:
        return self.raw  # callers serialize, never mutate


@dataclasses.dataclass(frozen=True)
class RetryBudgetSpec:
    """Cluster retry-budget config (``retryBudget:``): one per-model token
    bucket every retry source draws from (connect failover, handoff
    retries, stream resume, hedges) so localized failure cannot amplify
    into a cluster-wide retry storm. Rendered verbatim into router.json —
    a non-empty block enables the budget in both routers."""

    raw: dict = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        unknown = set(self.raw) - _RETRY_BUDGET_KEYS
        if unknown:
            raise SpecError(
                f"unknown retryBudget keys: {sorted(unknown)} "
                f"(known: {sorted(_RETRY_BUDGET_KEYS)})")
        for k, v in self.raw.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise SpecError(
                    f"retryBudget.{k} must be a number, got {v!r}")
            if v < 0:
                raise SpecError(f"retryBudget.{k} must be >= 0, got {v}")

    def to_wire(self) -> dict:
        return self.raw  # callers serialize, never mutate


@dataclasses.dataclass(frozen=True)
class PrefixAffinitySpec:
    """Prefix-affinity + KV-cache-aware routing config
    (``prefixAffinity:``): pins same-prefix sessions to a rendezvous-hashed
    replica and steers to peers whose advertised digest filters claim the
    request's prefix chain. Rendered verbatim into router.json — a
    non-empty block enables the layer in both routers; absent = dormant
    (pure P2C, byte-identical to the layer not existing)."""

    raw: dict = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        unknown = set(self.raw) - _AFFINITY_KEYS
        if unknown:
            raise SpecError(
                f"unknown prefixAffinity keys: {sorted(unknown)} "
                f"(known: {sorted(_AFFINITY_KEYS)})")
        for k, v in self.raw.items():
            if k in _AFFINITY_BOOL_KEYS:
                if not isinstance(v, bool):
                    raise SpecError(
                        f"prefixAffinity.{k} must be a bool, got {v!r}")
                continue
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise SpecError(
                    f"prefixAffinity.{k} must be a number, got {v!r}")
            if v < 0:
                raise SpecError(
                    f"prefixAffinity.{k} must be >= 0, got {v}")
        hashes = self.raw.get("filter_hashes")
        if hashes is not None and not (1 <= hashes <= 4):
            raise SpecError(
                f"prefixAffinity.filter_hashes must be in [1, 4], "
                f"got {hashes}")

    def to_wire(self) -> dict:
        return self.raw  # callers serialize, never mutate


@dataclasses.dataclass(frozen=True)
class TracingSpec:
    """Cross-hop distributed tracing config (``tracing:``): OTLP/HTTP
    endpoint for tail-sampled span export, the head sample rate for
    unremarkable traces, and the slow-trace threshold that forces export.
    Rendered verbatim into router.json and as LLMK_* env on the engine
    containers — absent = dormant (no exporter thread, no headers beyond
    the always-on traceparent propagation, rendering byte-identical)."""

    raw: dict = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        unknown = set(self.raw) - _TRACING_KEYS
        if unknown:
            raise SpecError(
                f"unknown tracing keys: {sorted(unknown)} "
                f"(known: {sorted(_TRACING_KEYS)})")
        ep = self.raw.get("otlpEndpoint")
        if ep is not None and not isinstance(ep, str):
            raise SpecError(
                f"tracing.otlpEndpoint must be a string, got {ep!r}")
        for k in ("sample", "tailSlowMs"):
            v = self.raw.get(k)
            if v is None:
                continue
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise SpecError(f"tracing.{k} must be a number, got {v!r}")
            if v < 0:
                raise SpecError(f"tracing.{k} must be >= 0, got {v}")
        sample = self.raw.get("sample")
        if sample is not None and sample > 1:
            raise SpecError(
                f"tracing.sample must be in [0, 1], got {sample}")

    def to_wire(self) -> dict:
        return self.raw  # callers serialize, never mutate


@dataclasses.dataclass(frozen=True)
class AdapterSpec:
    """One LoRA adapter a model's replicas serve (multi-tenant serving):
    requests address it as ``model: "<modelName>:<name>"``."""

    name: str
    huggingface_id: Optional[str] = None
    path: Optional[str] = None

    @property
    def ref(self) -> str:
        return self.path or self.huggingface_id or ""

    def validate(self, model_name: str) -> None:
        if not _NAME_RE.match(self.name):
            raise SpecError(
                f"model {model_name}: adapter name {self.name!r} must be a "
                f"DNS-1123 label (it becomes part of the model id "
                f"'{model_name}:{self.name}')"
            )
        if not self.ref:
            raise SpecError(
                f"model {model_name}: adapter {self.name!r} needs "
                f"huggingfaceId or path"
            )


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    model_name: str
    huggingface_id: Optional[str] = None
    model_path: Optional[str] = None       # local path (ramalama-equivalent)
    replicas: int = 1
    pvc_size: str = "30Gi"
    pvc_shared: bool = False               # ReadOnlyMany cache (fixes the
                                           # reference's RWO x replicas
                                           # deadlock, SURVEY §5 Checkpoint)
    tpu: Optional[TPUSpec] = dataclasses.field(default_factory=TPUSpec)
    sharding: ShardingSpec = dataclasses.field(default_factory=ShardingSpec)
    quantization: Optional[str] = None     # None | int8 | fp8 | awq
    max_model_len: int = 4096
    engine_args: tuple[str, ...] = ()      # passthrough (reference gap)
    # free-form k8s resources for CPU/local models (the ramalama chart's
    # verbatim `toYaml .resources` passthrough, reference
    # ramalama model-deployments.yaml:36-37); ignored when tpu is set
    resources: Optional[dict] = None
    dtype: Optional[str] = None            # engine --dtype override
    # fused decode window: tokens sampled per device dispatch
    # (LLMK_DECODE_STEPS); None = engine default. Multihost replicas
    # clamp to 1 at engine start until the broadcast protocol carries
    # the window, so the spec accepts it everywhere.
    decode_steps: Optional[int] = None
    # speculative decoding tier (LLMK_SPECULATION): None = off,
    # "ngram" = model-free prompt lookup, "draft" = small draft model.
    # `draft` names the draft checkpoint (registry name or .gguf path,
    # LLMK_DRAFT_MODEL) and implies speculation: draft. Needs
    # decodeSteps >= 2 — drafts ride the fused decode window.
    speculation: Optional[str] = None
    draft: Optional[str] = None
    # KV-cache storage dtype (LLMK_KV_DTYPE): None = full-width (the model
    # compute dtype), "int8" = quantized pages with per-token scales —
    # roughly 2x the resident streams per chip at equal HBM
    kv_dtype: Optional[str] = None
    # host-RAM offload tier capacity in GiB (LLMK_KV_HOST_CACHE_GB):
    # finished/preempted sessions park their KV pages in host memory and
    # a returning session re-uploads instead of re-prefilling. 0 = off.
    kv_host_cache_gb: float = 0.0
    # disaggregated serving role (LLMK_ROLE): "both" (default) keeps the
    # colocated prefill+decode replica; "prefill" replicas run chunked
    # prompt ingestion only and hand finished KV pages off via the host
    # tier (so kvHostCacheGB > 0 is required); "decode" replicas adopt
    # handed-off pages and run the fused K-step loop. Two models[]
    # entries MAY share one modelName iff their roles are exactly
    # {prefill, decode} — they render as separate Deployments that the
    # router composes into one two-hop serving path.
    role: str = "both"
    # goodput ledger (LLMK_LEDGER): per-request chip-time attribution +
    # MFU/MBU accounting. None = engine default (on); False disables the
    # per-dispatch bookkeeping entirely.
    ledger: Optional[bool] = None
    # step-time anomaly watchdog -> automatic profiler capture
    # (LLMK_ANOMALY_PROFILE / _Z / _COOLDOWN_S); None = engine defaults
    anomaly_profile: Optional[AnomalyProfileSpec] = None
    # multi-tenant LoRA: adapters served on this model's replicas, the
    # device slot count (LRU-recycled) and max rank the slots are sized for
    adapters: tuple = ()                   # tuple[AdapterSpec, ...]
    adapter_slots: int = 4
    adapter_rank: int = 16
    # closed-loop replica autoscaling; None = static replica count
    autoscaling: Optional[AutoscalingSpec] = None

    def validate(self) -> None:
        if not _NAME_RE.match(self.model_name):
            raise SpecError(
                f"modelName {self.model_name!r} must be a DNS-1123 label"
            )
        if not (self.huggingface_id or self.model_path):
            raise SpecError(
                f"model {self.model_name}: need huggingfaceId or modelPath"
            )
        scale_to_zero = (self.autoscaling is not None
                         and self.autoscaling.min_replicas == 0)
        if self.replicas < (0 if scale_to_zero else 1):
            raise SpecError(
                f"model {self.model_name}: replicas must be >= 1 "
                f"(0 only with autoscaling.minReplicas: 0 — scale-to-zero)")
        if self.decode_steps is not None and self.decode_steps < 1:
            raise SpecError(
                f"model {self.model_name}: decodeSteps must be >= 1, "
                f"got {self.decode_steps}"
            )
        if self.speculation not in (None, "ngram", "draft"):
            raise SpecError(
                f"model {self.model_name}: speculation must be 'ngram' or "
                f"'draft', got {self.speculation!r}"
            )
        if self.speculation == "draft" and not self.draft:
            raise SpecError(
                f"model {self.model_name}: speculation: draft needs a "
                f"draft: model reference (registry name or .gguf path)"
            )
        if self.draft and self.speculation == "ngram":
            raise SpecError(
                f"model {self.model_name}: draft: {self.draft!r} is unused "
                f"under speculation: ngram — drop one of them"
            )
        if self.speculation is not None and self.decode_steps is not None \
                and self.decode_steps < 2:
            raise SpecError(
                f"model {self.model_name}: speculation needs "
                f"decodeSteps >= 2 (drafts ride the fused decode window), "
                f"got decodeSteps: {self.decode_steps}"
            )
        if self.quantization not in (None, "int8", "fp8", "awq"):
            raise SpecError(
                f"model {self.model_name}: unknown quantization "
                f"{self.quantization!r}"
            )
        if self.kv_dtype not in (None, "int8"):
            raise SpecError(
                f"model {self.model_name}: kvDtype must be 'int8' or "
                f"omitted, got {self.kv_dtype!r}"
            )
        if self.kv_host_cache_gb < 0:
            raise SpecError(
                f"model {self.model_name}: kvHostCacheGB must be >= 0, "
                f"got {self.kv_host_cache_gb}"
            )
        if self.role not in ("prefill", "decode", "both"):
            raise SpecError(
                f"model {self.model_name}: role must be 'prefill', "
                f"'decode', or 'both', got {self.role!r}"
            )
        if self.role != "both" and self.tpu is not None \
                and self.tpu.multi_host:
            raise SpecError(
                f"model {self.model_name}: role: {self.role} is "
                f"unsupported on a multi-host slice (the KV handoff "
                f"rides the coordinator-local host tier, which multihost "
                f"rejects) — drop role: or use a single-host topology"
            )
        if self.role == "prefill" and self.kv_host_cache_gb <= 0:
            raise SpecError(
                f"model {self.model_name}: role: prefill needs "
                f"kvHostCacheGB > 0 — the handoff ticket points decode "
                f"replicas at pages spilled into the host tier, so a "
                f"prefill replica without one has nowhere to put them"
            )
        if (self.kv_host_cache_gb > 0 and self.tpu is not None
                and self.tpu.multi_host):
            raise SpecError(
                f"model {self.model_name}: kvHostCacheGB is unsupported on "
                f"a multi-host slice (page uploads are coordinator-local "
                f"and would desync follower pods) — drop it or use a "
                f"single-host topology"
            )
        if self.anomaly_profile is not None:
            self.anomaly_profile.validate(self.model_name)
            if self.ledger is False and self.anomaly_profile.enabled:
                raise SpecError(
                    f"model {self.model_name}: anomalyProfile needs the "
                    f"goodput ledger (the watchdog reads its per-dispatch "
                    f"times) — drop `ledger: false` or disable the "
                    f"watchdog"
                )
        if self.tpu is not None:
            if self.tpu.accelerator not in CHIPS_PER_HOST:
                raise SpecError(
                    f"model {self.model_name}: unknown accelerator "
                    f"{self.tpu.accelerator!r} (known: {sorted(CHIPS_PER_HOST)})"
                )
            self.tpu.resolved_topology()
            self.sharding.resolve(self.tpu.chips)
        # peak replica count: what the autoscaler may scale up to, not
        # just the static spec — an HPA-driven second replica hits the
        # same RWO volume-attach deadlock as a static replicas: 2
        peak = self.replicas
        if self.autoscaling is not None:
            self.autoscaling.validate(self.model_name)
            peak = max(peak, self.autoscaling.max_replicas)
            if self.tpu is not None and self.tpu.multi_host:
                raise SpecError(
                    f"model {self.model_name}: autoscaling targets the "
                    f"replica count, but a multi-host slice's StatefulSet "
                    f"replicas are the pod GROUP size ({self.tpu.hosts} "
                    f"hosts); autoscaling multi-host models is unsupported"
                )
        if peak > 1 and not self.pvc_shared and self.huggingface_id:
            raise SpecError(
                f"model {self.model_name}: up to {peak} replicas with a "
                f"ReadWriteOnce cache PVC deadlocks on volume attach; set "
                f"pvcShared: true (ReadOnlyMany) or replicas: 1"
            )
        anames = [a.name for a in self.adapters]
        adupes = {n for n in anames if anames.count(n) > 1}
        if adupes:
            raise SpecError(
                f"model {self.model_name}: duplicate adapter name(s): "
                f"{sorted(adupes)}"
            )
        for a in self.adapters:
            a.validate(self.model_name)
        if self.adapters and (self.adapter_slots < 1 or self.adapter_rank < 1):
            raise SpecError(
                f"model {self.model_name}: adapterSlots and adapterRank "
                f"must be >= 1"
            )


@dataclasses.dataclass(frozen=True)
class DeploySpec:
    models: tuple[ModelSpec, ...]
    namespace: str = "tpu-models"
    image: str = "llms-on-kubernetes-tpu:latest"
    image_pull_policy: str = "IfNotPresent"
    storage_class: Optional[str] = None
    default_model: Optional[str] = None    # router fallback; first if None
    strict_routing: bool = False           # 404 unknown models (reference
                                           # silently fell back, SURVEY §3.1)
    native_router: bool = True             # C++ router image vs python
    # router-side active /ready probe period per replica; 0 disables
    probe_interval_s: float = 2.0
    # zero-drop streams (ISSUE 9): mid-stream journal resume on upstream
    # death (on by default), capped re-issues per stream, and hedged
    # first-byte requests (0 = off). Rendered into router.json — both the
    # native C++ router and the python router parse the same keys.
    stream_resume: bool = True
    resume_attempts: int = 2
    hedge_ms: float = 0.0
    # disaggregated serving: attempts across decode replicas to place a
    # prefill handoff ticket before falling back to a colocated replica
    # (LLMK_HANDOFF_RETRIES in both routers)
    handoff_retries: int = 2
    # per-tenant QoS at the gateway (ISSUE 10); None = QoS disabled
    qos: Optional[QoSSpec] = None
    # gray-failure layer (ISSUE 17): latency/error outlier ejection and
    # cluster retry budgets; None = layer disabled (dormant in routers)
    outlier_ejection: Optional[OutlierEjectionSpec] = None
    retry_budget: Optional[RetryBudgetSpec] = None
    # prefix-affinity + cache-aware routing (ISSUE 18); None = dormant
    prefix_affinity: Optional[PrefixAffinitySpec] = None
    # cross-hop distributed tracing (ISSUE 19); None = dormant (no OTLP
    # exporter; traceparent propagation itself is always on)
    tracing: Optional[TracingSpec] = None
    webui_enabled: bool = True
    webui_name: str = "TPU Multi-Model WebUI"
    hf_secret_name: str = "huggingface-token"
    host_model_path: Optional[str] = None  # local path mount (CPU profile)
    # Prometheus address the KEDA ScaledObject triggers query
    prometheus_url: str = DEFAULT_PROMETHEUS_URL

    def validate(self) -> None:
        if not self.models:
            raise SpecError("at least one model is required")
        names = [m.model_name for m in self.models]
        # one entry per name, with one exception: a disaggregated pair —
        # exactly two entries whose roles are {prefill, decode} — shares
        # the modelName so the router serves them as one model
        by_name: dict[str, list[str]] = {}
        for m in self.models:
            by_name.setdefault(m.model_name, []).append(m.role)
        for name, roles in by_name.items():
            if len(roles) == 1:
                continue
            if sorted(roles) != ["decode", "prefill"]:
                raise SpecError(
                    f"duplicate modelName(s): ['{name}'] (two entries may "
                    f"share a modelName only as a disaggregated pair with "
                    f"roles prefill + decode; got roles {sorted(roles)})")
        for m in self.models:
            m.validate()
        if self.default_model is not None and self.default_model not in names:
            raise SpecError(
                f"defaultModel {self.default_model!r} is not in models[] "
                f"({names})"
            )
        if self.resume_attempts < 0:
            raise SpecError(
                f"router.resumeAttempts must be >= 0, got "
                f"{self.resume_attempts}")
        if self.hedge_ms < 0:
            raise SpecError(
                f"router.hedgeMs must be >= 0, got {self.hedge_ms}")
        if self.handoff_retries < 0:
            raise SpecError(
                f"router.handoffRetries must be >= 0, got "
                f"{self.handoff_retries}")
        if self.qos is not None:
            self.qos.validate()
        if self.outlier_ejection is not None:
            self.outlier_ejection.validate()
        if self.retry_budget is not None:
            self.retry_budget.validate()
        if self.prefix_affinity is not None:
            self.prefix_affinity.validate()
        if self.tracing is not None:
            self.tracing.validate()

    @property
    def resolved_default(self) -> str:
        return self.default_model or self.models[0].model_name


# ---------------------------------------------------------------------------
# YAML / dict loading (the values.yaml surface)
# ---------------------------------------------------------------------------

def _tpu_from(d: Optional[dict]) -> Optional[TPUSpec]:
    if d is None:
        return None
    unknown = set(d) - {"accelerator", "chips", "topology", "hosts"}
    if unknown:
        raise SpecError(f"unknown tpu keys: {sorted(unknown)}")
    hosts = d.get("hosts")
    if hosts is not None:
        if int(hosts) < 1:
            raise SpecError(f"tpu.hosts must be >= 1, got {hosts}")
        chips = int(d.get("chips", 8))
        if chips % int(hosts) != 0:
            raise SpecError(
                f"tpu.chips={chips} not divisible by tpu.hosts={hosts} — "
                f"every slice host carries the same chip count"
            )
    return TPUSpec(
        accelerator=d.get("accelerator", "v5e"),
        chips=int(d.get("chips", 8)),
        topology=d.get("topology"),
        hosts_override=int(hosts) if hosts is not None else None,
    )


def _autoscaling_from(d: Optional[dict], model_name: str) \
        -> Optional[AutoscalingSpec]:
    if d is None:
        return None
    if not isinstance(d, dict):
        raise SpecError(
            f"model {model_name}: autoscaling must be a mapping")
    unknown = set(d) - {"minReplicas", "maxReplicas", "queueDepthTarget",
                        "ttftOkRatioFloor"}
    if unknown:
        raise SpecError(
            f"model {model_name}: unknown autoscaling keys: "
            f"{sorted(unknown)}")
    return AutoscalingSpec(
        min_replicas=int(d.get("minReplicas", 1)),
        max_replicas=int(d.get("maxReplicas", 4)),
        queue_depth_target=int(d.get("queueDepthTarget", 8)),
        ttft_ok_ratio_floor=float(d.get("ttftOkRatioFloor", 0.95)),
    )


def _anomaly_from(d: Optional[dict], model_name: str) \
        -> Optional[AnomalyProfileSpec]:
    if d is None:
        return None
    if not isinstance(d, dict):
        raise SpecError(
            f"model {model_name}: anomalyProfile must be a mapping")
    unknown = set(d) - {"enabled", "threshold", "cooldownS"}
    if unknown:
        raise SpecError(
            f"model {model_name}: unknown anomalyProfile keys: "
            f"{sorted(unknown)}")
    return AnomalyProfileSpec(
        enabled=bool(d.get("enabled", True)),
        threshold=float(d.get("threshold", 4.0)),
        cooldown_s=float(d.get("cooldownS", 600.0)),
    )


def _tenant_qos_from(d, label: str) -> TenantQoSSpec:
    if not isinstance(d, dict):
        raise SpecError(f"qos {label}: must be a mapping")
    unknown = set(d) - {"weight", "priority", "rps", "burst",
                        "tokens_per_min"}
    if unknown:
        raise SpecError(f"qos {label}: unknown keys: {sorted(unknown)}")
    return TenantQoSSpec(
        weight=float(d.get("weight", 1.0)),
        priority=d.get("priority"),
        rps=float(d.get("rps", 0.0)),
        burst=float(d.get("burst", 0.0)),
        tokens_per_min=float(d.get("tokens_per_min", 0.0)),
    )


def _qos_from(d: Optional[dict]) -> Optional[QoSSpec]:
    if not d:
        # absent OR empty block = disabled (matches both routers'
        # truthiness: empty tenants/default/brownout do not enable QoS)
        return None
    if not isinstance(d, dict):
        raise SpecError("qos must be a mapping")
    unknown = set(d) - {"tenants", "default", "brownout"}
    if unknown:
        raise SpecError(f"unknown qos keys: {sorted(unknown)}")
    tenants_raw = d.get("tenants") or {}
    if not isinstance(tenants_raw, dict):
        raise SpecError("qos.tenants must be a mapping of tenant -> entry")
    brownout = d.get("brownout")
    if brownout is not None and not isinstance(brownout, dict):
        raise SpecError("qos.brownout must be a mapping")
    if brownout:
        unknown = set(brownout) - {"queue_depth_hi", "burn_rate_hi",
                                   "clamp_max_tokens"}
        if unknown:
            raise SpecError(f"unknown qos.brownout keys: {sorted(unknown)}")
    b = brownout or {}
    return QoSSpec(
        tenants=tuple(sorted(
            (str(n), _tenant_qos_from(t, f"tenant {n!r}"))
            for n, t in tenants_raw.items())),
        default=(_tenant_qos_from(d["default"], "default")
                 if d.get("default") else None),
        has_brownout=bool(brownout),
        queue_depth_hi=float(b.get("queue_depth_hi", 0.0)),
        burn_rate_hi=float(b.get("burn_rate_hi", 0.0)),
        clamp_max_tokens=int(b.get("clamp_max_tokens", 64)),
        raw=d,
    )


def _outlier_from(d: Optional[dict]) -> Optional[OutlierEjectionSpec]:
    if not d:
        # absent OR empty block = disabled (matches both routers'
        # truthiness: enabled iff the wire block is non-empty)
        return None
    if not isinstance(d, dict):
        raise SpecError("outlierEjection must be a mapping")
    return OutlierEjectionSpec(raw=d)


def _retry_budget_from(d: Optional[dict]) -> Optional[RetryBudgetSpec]:
    if not d:
        return None
    if not isinstance(d, dict):
        raise SpecError("retryBudget must be a mapping")
    return RetryBudgetSpec(raw=d)


def _affinity_from(d: Optional[dict]) -> Optional[PrefixAffinitySpec]:
    if not d:
        return None
    if not isinstance(d, dict):
        raise SpecError("prefixAffinity must be a mapping")
    return PrefixAffinitySpec(raw=d)


def _tracing_from(d: Optional[dict]) -> Optional[TracingSpec]:
    if not d:
        return None
    if not isinstance(d, dict):
        raise SpecError("tracing must be a mapping")
    return TracingSpec(raw=d)


def _adapter_from(d: dict, model_name: str) -> AdapterSpec:
    if not isinstance(d, dict):
        raise SpecError(
            f"model {model_name}: adapters[] entries must be mappings")
    unknown = set(d) - {"name", "huggingfaceId", "path"}
    if unknown:
        raise SpecError(
            f"model {model_name}: unknown adapter keys: {sorted(unknown)}")
    return AdapterSpec(
        name=str(d.get("name", "")),
        huggingface_id=d.get("huggingfaceId"),
        path=d.get("path"),
    )


def _model_from(d: dict) -> ModelSpec:
    known = {
        "modelName", "huggingfaceId", "modelPath", "replicas", "pvcSize",
        "pvcShared", "tpu", "sharding", "quantization", "maxModelLen",
        "engineArgs", "resources", "dtype", "decodeSteps",
        "speculation", "draft", "kvDtype", "kvHostCacheGB", "role",
        "ledger", "anomalyProfile",
        "adapters", "adapterSlots", "adapterRank", "autoscaling",
    }
    unknown = set(d) - known
    if unknown:
        raise SpecError(
            f"unknown model keys: {sorted(unknown)} (known: {sorted(known)})"
        )
    sh = d.get("sharding") or {}
    return ModelSpec(
        model_name=d.get("modelName", ""),
        huggingface_id=d.get("huggingfaceId"),
        model_path=d.get("modelPath"),
        replicas=int(d.get("replicas", 1)),
        pvc_size=str(d.get("pvcSize", "30Gi")),
        pvc_shared=bool(d.get("pvcShared", False)),
        # modelPath without an explicit tpu block = the local/CPU profile
        # (the ramalama-equivalent contract has no accelerator at all)
        tpu=(_tpu_from(d["tpu"]) if "tpu" in d
             else (None if d.get("modelPath") else TPUSpec())),
        sharding=ShardingSpec(
            tp=int(sh.get("tp", 0)), ep=int(sh.get("ep", 1)),
            data=int(sh.get("data", 1)),
        ),
        quantization=d.get("quantization"),
        max_model_len=int(d.get("maxModelLen", 4096)),
        engine_args=tuple(d.get("engineArgs", ())),
        resources=d.get("resources"),
        dtype=d.get("dtype"),
        decode_steps=(int(d["decodeSteps"]) if "decodeSteps" in d
                      else None),
        # draft: alone implies speculation: draft (mirrors EngineConfig)
        speculation=(d.get("speculation")
                     or ("draft" if d.get("draft") else None)),
        draft=d.get("draft"),
        kv_dtype=d.get("kvDtype"),
        kv_host_cache_gb=float(d.get("kvHostCacheGB", 0) or 0),
        role=str(d.get("role", "both") or "both"),
        ledger=(bool(d["ledger"]) if "ledger" in d else None),
        anomaly_profile=_anomaly_from(d.get("anomalyProfile"),
                                      d.get("modelName", "")),
        adapters=tuple(_adapter_from(a, d.get("modelName", ""))
                       for a in d.get("adapters", ()) or ()),
        adapter_slots=int(d.get("adapterSlots", 4)),
        adapter_rank=int(d.get("adapterRank", 16)),
        autoscaling=_autoscaling_from(d.get("autoscaling"),
                                      d.get("modelName", "")),
    )


def load_spec(source: "str | dict") -> DeploySpec:
    """Load + validate a DeploySpec from a YAML path/string or a dict."""
    import yaml

    if isinstance(source, str):
        if "\n" not in source and source.endswith((".yaml", ".yml")):
            with open(source) as f:
                data = yaml.safe_load(f)
        else:
            data = yaml.safe_load(source)
    else:
        data = source
    if not isinstance(data, dict):
        raise SpecError("config must be a mapping")

    models = tuple(_model_from(m) for m in data.get("models", ()))
    webui = data.get("webui", {}) or {}
    image = data.get("image", {}) or {}
    if isinstance(image, dict):
        repo = image.get("repository", "llms-on-kubernetes-tpu")
        tag = image.get("tag", "latest")
        image_str = f"{repo}:{tag}"
        pull = image.get("pullPolicy", "IfNotPresent")
    else:
        image_str, pull = str(image), "IfNotPresent"
    spec = DeploySpec(
        models=models,
        namespace=data.get("namespace", "tpu-models"),
        image=image_str,
        image_pull_policy=pull,
        storage_class=(data.get("storage") or {}).get("className"),
        default_model=(data.get("router") or {}).get("defaultModel"),
        strict_routing=bool((data.get("router") or {}).get("strict", False)),
        native_router=bool((data.get("router") or {}).get("native", True)),
        probe_interval_s=float(
            (data.get("router") or {}).get("probeIntervalS", 2.0)),
        stream_resume=bool(
            (data.get("router") or {}).get("streamResume", True)),
        resume_attempts=int(
            (data.get("router") or {}).get("resumeAttempts", 2)),
        hedge_ms=float((data.get("router") or {}).get("hedgeMs", 0.0)),
        handoff_retries=int(
            (data.get("router") or {}).get("handoffRetries", 2)),
        qos=_qos_from(data.get("qos")),
        outlier_ejection=_outlier_from(data.get("outlierEjection")),
        retry_budget=_retry_budget_from(data.get("retryBudget")),
        prefix_affinity=_affinity_from(data.get("prefixAffinity")),
        tracing=_tracing_from(data.get("tracing")),
        webui_enabled=bool(webui.get("enabled", True)),
        webui_name=webui.get("name", "TPU Multi-Model WebUI"),
        hf_secret_name=data.get("hfSecretName", "huggingface-token"),
        host_model_path=data.get("hostModelPath"),
        prometheus_url=str(data.get("prometheusUrl")
                           or DEFAULT_PROMETHEUS_URL),
    )
    spec.validate()
    return spec

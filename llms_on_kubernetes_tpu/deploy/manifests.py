"""Kubernetes manifest renderer: models[] spec → the full serving topology.

The TPU-native equivalent of the reference chart's template fan-out
(reference vllm-models/helm-chart/templates/: model-deployments.yaml,
model-services.yaml, model-pvcs.yaml, model-gateway.yaml,
webui-deployment.yaml, gateway.yaml — SURVEY §3.2 "config fan-out"). Per
model it emits:

- single-host (tpu.hosts == 1): a Deployment requesting
  ``google.com/tpu: <chips>`` with GKE TPU nodeSelectors
  (``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology``) — the
  analogue of the reference's ``nvidia.com/gpu`` requests + taint
  tolerations (model-deployments.yaml:40-44,75-78);
- multi-host (v5p-16 etc.): a StatefulSet pod group + headless Service for
  stable worker DNS, each pod one slice host, with
  ``jax.distributed``-compatible env (coordinator = pod 0) — the
  capability the reference lacked entirely (SURVEY §2.4);
- a ClusterIP Service per model, an optional HF-cache PVC (ReadOnlyMany
  opt-in to fix the reference's RWO x replicas deadlock, SURVEY §5);
- the router ConfigMap/Deployment/Service with a **config-hash pod
  annotation** so ArgoCD syncing a model-list change rolls the router —
  the reference's gateway silently kept stale routes until manually
  restarted (SURVEY §3.2);
- Istio Gateway + VirtualService (same 4-route shape as the reference:
  exact /v1/models, prefix /v1/, /health, / → webui);
- OpenWebUI Deployment/Service/PVC pointed at the router.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional

from llms_on_kubernetes_tpu.deploy.spec import DeploySpec, ModelSpec

Manifest = dict[str, Any]

ENGINE_PORT = 8080
ROUTER_PORT = 8080
WEBUI_PORT = 8080

# Drain budget (k8s/tpu-models/README.md "Graceful shutdown / rollout"):
# the preStop sleep holds SIGTERM until endpoint removal has propagated,
# then the server's drain (in-flight generations complete, /ready -> 503)
# must finish inside the grace period or the kubelet SIGKILLs mid-stream.
PRESTOP_SLEEP_S = 5
MODEL_GRACE_S = 330    # worst-case long generation + preStop sleep
ROUTER_GRACE_S = 30    # router only relays; in-flight proxying is short


def _res_name(m: ModelSpec) -> str:
    """Kubernetes resource base name for one models[] entry. A
    disaggregated pair shares its modelName, so role-specific entries get
    a role suffix — separate Deployments/Services per pool, while the
    router still serves them as one model."""
    base = f"model-{m.model_name}"
    return base if m.role == "both" else f"{base}-{m.role}"


def _lifecycle() -> dict[str, Any]:
    return {"lifecycle": {"preStop": {"exec": {
        "command": ["sh", "-c", f"sleep {PRESTOP_SLEEP_S}"]}}}}


def _labels(app: str, component: str) -> dict[str, str]:
    return {
        "app": app,
        "app.kubernetes.io/component": component,
        "app.kubernetes.io/part-of": "llms-on-kubernetes-tpu",
    }


def _meta(name: str, spec: DeploySpec, component: str,
          annotations: Optional[dict] = None) -> Manifest:
    meta: Manifest = {
        "name": name,
        "namespace": spec.namespace,
        "labels": _labels(name, component),
    }
    if annotations:
        meta["annotations"] = annotations
    return meta


def _engine_args(m: ModelSpec, spec: DeploySpec) -> list[str]:
    ref = m.huggingface_id or m.model_path
    args = [
        "serve",
        "--model", str(ref),
        "--served-model-name", m.model_name,
        "--host", "0.0.0.0",
        "--port", str(ENGINE_PORT),
    ]
    if m.tpu is not None:
        sh = m.sharding.resolve(m.tpu.chips)
        args += ["--tensor-parallel-size", str(sh.tp)]
        if sh.ep > 1:
            args += ["--expert-parallel-size", str(sh.ep)]
    if m.quantization:
        args += ["--quantization", m.quantization]
    if m.dtype:
        args += ["--dtype", m.dtype]
    for a in m.adapters:
        args += ["--adapter", f"{a.name}={a.ref}"]
    if m.adapters:
        args += ["--adapter-slots", str(m.adapter_slots),
                 "--adapter-rank", str(m.adapter_rank)]
    args += list(m.engine_args)
    return args


def _probes() -> dict[str, Any]:
    """Same probe budget as the reference's vLLM pods (cold start can include
    an HF download; reference model-deployments.yaml:48-63)."""
    return {
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": ENGINE_PORT},
            "initialDelaySeconds": 120, "periodSeconds": 30,
            "failureThreshold": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": ENGINE_PORT},
            "initialDelaySeconds": 300, "periodSeconds": 60,
            "failureThreshold": 5,
        },
    }


def _engine_container(m: ModelSpec, spec: DeploySpec) -> Manifest:
    c: Manifest = {
        "name": "engine",
        "image": spec.image,
        "imagePullPolicy": spec.image_pull_policy,
        "command": ["python", "-m", "llms_on_kubernetes_tpu"],
        "args": _engine_args(m, spec),
        "ports": [
            {"containerPort": ENGINE_PORT, "name": "http"},
        ],
        "env": [
            {"name": "HUGGING_FACE_HUB_TOKEN", "valueFrom": {"secretKeyRef": {
                "name": spec.hf_secret_name, "key": "token",
                "optional": True,
            }}},
        ],
        **_probes(),
        **_lifecycle(),
    }
    if m.decode_steps is not None:
        # env (not an engine arg) so the fused-decode window stays out
        # of the argv contract the golden tests pin; the engine clamps
        # to 1 on multihost regardless of what the spec asks for
        c["env"].append({"name": "LLMK_DECODE_STEPS",
                         "value": str(m.decode_steps)})
    if m.speculation is not None:
        # same env convention as the decode window; the engine ignores
        # speculation on multihost after its decode_steps clamp
        c["env"].append({"name": "LLMK_SPECULATION",
                         "value": m.speculation})
    if m.draft is not None:
        c["env"].append({"name": "LLMK_DRAFT_MODEL", "value": m.draft})
    if m.kv_dtype is not None:
        # env, like the decode window: KV storage width is an engine
        # runtime knob, not part of the pinned argv contract
        c["env"].append({"name": "LLMK_KV_DTYPE", "value": m.kv_dtype})
    if m.kv_host_cache_gb > 0:
        c["env"].append({"name": "LLMK_KV_HOST_CACHE_GB",
                         "value": str(m.kv_host_cache_gb)})
    if m.role != "both":
        # disaggregated serving role; the engine validates the combo
        # (prefill needs the host tier, multihost rejects roles)
        c["env"].append({"name": "LLMK_ROLE", "value": m.role})
    if m.ledger is not None:
        # goodput ledger on/off; engine default is on, so only an
        # explicit spec value renders env
        c["env"].append({"name": "LLMK_LEDGER",
                         "value": "1" if m.ledger else "0"})
    if m.anomaly_profile is not None:
        ap = m.anomaly_profile
        c["env"].append({"name": "LLMK_ANOMALY_PROFILE",
                         "value": "1" if ap.enabled else "0"})
        c["env"].append({"name": "LLMK_ANOMALY_Z",
                         "value": str(ap.threshold)})
        c["env"].append({"name": "LLMK_ANOMALY_COOLDOWN_S",
                         "value": str(ap.cooldown_s)})
    if spec.prefix_affinity is not None:
        # cache-aware routing (ISSUE 18): the replica's /ready filter
        # geometry must match what the router config promised, so the
        # spec block's bits/hashes thread through to the API server
        aff = spec.prefix_affinity.to_wire()
        if "filter_bits" in aff:
            c["env"].append({"name": "LLMK_PREFIX_FILTER_BITS",
                             "value": str(int(aff["filter_bits"]))})
        if "filter_hashes" in aff:
            c["env"].append({"name": "LLMK_PREFIX_FILTER_HASHES",
                             "value": str(int(aff["filter_hashes"]))})
    if spec.tracing is not None:
        # cross-hop tracing (ISSUE 19): the engine fragments export to
        # the same OTLP endpoint under the same sampling policy as the
        # router's, so stitched trees are complete on the backend too
        tr = spec.tracing.to_wire()
        if tr.get("otlpEndpoint"):
            c["env"].append({"name": "LLMK_OTLP_ENDPOINT",
                             "value": str(tr["otlpEndpoint"])})
        if "sample" in tr:
            c["env"].append({"name": "LLMK_TRACE_SAMPLE",
                             "value": str(float(tr["sample"]))})
        if "tailSlowMs" in tr:
            c["env"].append({"name": "LLMK_SLOW_REQUEST_MS",
                             "value": str(float(tr["tailSlowMs"]))})
    if m.tpu is None:
        # local/CPU profile: force the XLA-CPU backend (same env the
        # local-models chart sets) so the TPU-enabled image runs on
        # accelerator-less nodes
        c["env"].append({"name": "JAX_PLATFORMS", "value": "cpu"})
    if m.tpu is not None:
        c["resources"] = {
            "requests": {"google.com/tpu": str(m.tpu.chips_per_host)},
            "limits": {"google.com/tpu": str(m.tpu.chips_per_host)},
        }
    elif m.resources:
        # local/CPU profile: verbatim passthrough, like the reference's
        # `toYaml .resources` (ramalama model-deployments.yaml:36-37)
        c["resources"] = m.resources
    if m.huggingface_id:
        c["volumeMounts"] = [{
            "name": "hf-cache", "mountPath": "/root/.cache/huggingface",
        }]
    elif spec.host_model_path:
        c["volumeMounts"] = [{
            "name": "models", "mountPath": "/mnt/models", "readOnly": True,
        }]
    return c


def _tpu_node_selector(m: ModelSpec) -> dict[str, str]:
    assert m.tpu is not None
    return {
        "cloud.google.com/gke-tpu-accelerator": m.tpu.gke_accelerator,
        "cloud.google.com/gke-tpu-topology": m.tpu.resolved_topology(),
    }


def _volumes(m: ModelSpec, spec: DeploySpec) -> list[Manifest]:
    if m.huggingface_id:
        return [{
            "name": "hf-cache",
            "persistentVolumeClaim": {
                "claimName": f"{_res_name(m)}-cache",
                **({"readOnly": True} if m.pvc_shared else {}),
            },
        }]
    if spec.host_model_path:
        return [{
            "name": "models",
            "hostPath": {"path": spec.host_model_path, "type": "Directory"},
        }]
    return []


def _scrape_annotations(port: int = ENGINE_PORT) -> dict[str, str]:
    """Prometheus scrape hints for a pod's /metrics (SURVEY §5: the
    reference never scraped its engines' metrics endpoints)."""
    return {
        "prometheus.io/scrape": "true",
        "prometheus.io/port": str(port),
        "prometheus.io/path": "/metrics",
    }


def render_model_single_host(m: ModelSpec, spec: DeploySpec) -> list[Manifest]:
    pod_spec: Manifest = {
        "terminationGracePeriodSeconds": MODEL_GRACE_S,
        "containers": [_engine_container(m, spec)],
        "volumes": _volumes(m, spec),
    }
    if m.tpu is not None:
        pod_spec["nodeSelector"] = _tpu_node_selector(m)
    name = _res_name(m)
    dep = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(name, spec, "model-server"),
        "spec": {
            "replicas": m.replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {
                    "labels": _labels(name, "model-server"),
                    "annotations": _scrape_annotations(),
                },
                "spec": pod_spec,
            },
        },
    }
    return [dep]


def render_model_multi_host(m: ModelSpec, spec: DeploySpec) -> list[Manifest]:
    """One logical server spanning a pod group (v5p-16 ⇒ 4 pods × 4 chips).

    StatefulSet + headless Service gives each worker a stable DNS name;
    pod 0 is the ``jax.distributed`` coordinator and the only pod the
    model Service routes requests to (workers follow the jit program).
    SURVEY §7 hard-part 3: the reference's single-pod Deployment shape
    could never express this.
    """
    assert m.tpu is not None and m.tpu.multi_host
    name = f"model-{m.model_name}"
    headless = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"{name}-workers", spec, "model-worker-discovery"),
        "spec": {
            "clusterIP": "None",
            "publishNotReadyAddresses": True,
            "selector": {"app": name},
            "ports": [{"port": ENGINE_PORT, "name": "http"}],
        },
    }
    container = _engine_container(m, spec)
    container["env"] += [
        {"name": "POD_NAME", "valueFrom": {
            "fieldRef": {"fieldPath": "metadata.name"}}},
        {"name": "TPU_WORKER_HOSTNAMES", "value": ",".join(
            f"{name}-{i}.{name}-workers.{spec.namespace}.svc.cluster.local"
            for i in range(m.tpu.hosts)
        )},
        {"name": "JAX_COORDINATOR_ADDRESS", "value":
            f"{name}-0.{name}-workers.{spec.namespace}.svc.cluster.local:8476"},
        {"name": "JAX_NUM_PROCESSES", "value": str(m.tpu.hosts)},
    ]
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": _meta(name, spec, "model-server"),
        "spec": {
            "serviceName": f"{name}-workers",
            "replicas": m.tpu.hosts,
            "podManagementPolicy": "Parallel",  # gang start: all workers at once
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {
                    "labels": _labels(name, "model-server"),
                    "annotations": _scrape_annotations(),
                },
                "spec": {
                    "subdomain": f"{name}-workers",
                    "terminationGracePeriodSeconds": MODEL_GRACE_S,
                    "nodeSelector": _tpu_node_selector(m),
                    "containers": [container],
                    "volumes": _volumes(m, spec),
                },
            },
        },
    }
    return [headless, sts]


def render_model_service(m: ModelSpec, spec: DeploySpec) -> Manifest:
    name = _res_name(m)
    selector: Manifest = {"app": name}
    if m.tpu is not None and m.tpu.multi_host:
        # only the coordinator pod serves HTTP
        selector = {"statefulset.kubernetes.io/pod-name": f"{name}-0"}
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(name, spec, "model-service"),
        "spec": {
            "type": "ClusterIP",
            "selector": selector,
            "ports": [{"port": ENGINE_PORT, "targetPort": ENGINE_PORT,
                       "name": "http"}],
        },
    }


def _peak_replicas(m: ModelSpec) -> int:
    """Most replicas this model can ever run: the static count, or the
    autoscaler's ceiling when one is configured. Routing topology (the
    headless -replicas Service) keys off this, not the instantaneous
    count — an HPA scaling 1 -> 4 must not change the backend URL."""
    if m.autoscaling is not None:
        return max(m.replicas, m.autoscaling.max_replicas)
    return m.replicas


def render_model_replica_service(m: ModelSpec,
                                 spec: DeploySpec) -> Optional[Manifest]:
    """Headless Service over a replicated single-host model's pods.

    The router targets this for replicas > 1: headless DNS resolves to the
    ready pod IPs directly, so a new connection after a failover retry can
    land on a different replica than the one that just refused (a ClusterIP
    Service would be a single conntrack-balanced VIP hiding the replicas).
    """
    if _peak_replicas(m) <= 1 or (m.tpu is not None and m.tpu.multi_host):
        return None
    name = _res_name(m)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"{name}-replicas", spec, "model-replicas"),
        "spec": {
            "clusterIP": "None",
            "selector": {"app": name},
            "ports": [{"port": ENGINE_PORT, "name": "http"}],
        },
    }


# Scale-down damping shared by the HPA behavior block and the KEDA
# cooldownPeriod: one replica per minute after a 5-minute quiet window,
# so a burst's tail never mass-SIGTERMs replicas mid-stream (each removal
# still runs the full drain lifecycle: preStop sleep + graceful drain).
SCALE_DOWN_STABILIZATION_S = 300


def _ttft_miss_milli(a) -> int:
    """TTFT miss-ratio threshold in thousandths (k8s quantity millis):
    a 0.95 attainment floor = scale out beyond 50m missed."""
    return int(round((1.0 - a.ttft_ok_ratio_floor) * 1000))


def render_model_autoscaler(m: ModelSpec,
                            spec: DeploySpec) -> Optional[Manifest]:
    """One autoscaler per model with an ``autoscaling:`` block.

    minReplicas >= 1: an ``autoscaling/v2`` HPA. ``llm_queue_depth`` is a
    per-pod series (prometheus-adapter exposes it on the custom-metrics
    API from the pods' own /metrics scrape); TTFT attainment rides along
    as an Object metric on the api-gateway Service — the router emits
    ``llm_slo_ttft_miss_ratio`` over its sliding SLO window, so the
    target is the MISS ratio (HPA scales up when a Value metric exceeds
    its target; the ok-ratio would have the inverted sign).

    minReplicas == 0: a KEDA ScaledObject (the HPA cannot reach zero).
    Its prometheus triggers query the series Prometheus scrapes from the
    router's ``/metrics/cluster``; the queue trigger adds the router-side
    arrival rate (``llm_router_requests_total``) so a fully scaled-to-
    zero model — whose engines emit no queue depth at all — still wakes
    on incoming demand.

    Disaggregated roles scale on the signal their pool actually bounds:
    a ``prefill`` pool on its own queue depth only (tickets waiting for
    prompt ingestion; gateway TTFT conflates both pools), a ``decode``
    pool on the TTFT-attainment Object metric only (the decode fleet is
    what keeps streams flowing; its queue is by construction shallow).
    """
    a = m.autoscaling
    if a is None:
        return None
    name = _res_name(m)
    queue_metric = {"type": "Pods", "pods": {
        "metric": {"name": "llm_queue_depth"},
        "target": {"type": "AverageValue",
                   "averageValue": str(a.queue_depth_target)}}}
    ttft_metric = {"type": "Object", "object": {
        "metric": {"name": "llm_slo_ttft_miss_ratio"},
        "describedObject": {"apiVersion": "v1",
                            "kind": "Service",
                            "name": "api-gateway"},
        "target": {"type": "Value",
                   "value": f"{_ttft_miss_milli(a)}m"}}}
    if m.role == "prefill":
        metrics = [queue_metric]
    elif m.role == "decode":
        metrics = [ttft_metric]
    else:
        metrics = [queue_metric, ttft_metric]
    if a.min_replicas >= 1:
        return {
            "apiVersion": "autoscaling/v2",
            "kind": "HorizontalPodAutoscaler",
            "metadata": _meta(name, spec, "autoscaler"),
            "spec": {
                "scaleTargetRef": {"apiVersion": "apps/v1",
                                   "kind": "Deployment", "name": name},
                "minReplicas": a.min_replicas,
                "maxReplicas": a.max_replicas,
                "metrics": metrics,
                "behavior": {"scaleDown": {
                    "stabilizationWindowSeconds": SCALE_DOWN_STABILIZATION_S,
                    "policies": [{"type": "Pods", "value": 1,
                                  "periodSeconds": 60}],
                }},
            },
        }
    role_sel = "" if m.role == "both" else f',role="{m.role}"'
    queue_query = (
        f'sum(llm_queue_depth{{model="{m.model_name}"{role_sel}}}) + '
        f'sum(rate(llm_router_requests_total{{model="{m.model_name}"}}[1m]))'
    )
    queue_trigger = {"type": "prometheus", "metadata": {
        "serverAddress": spec.prometheus_url,
        "metricName": "llm_queue_depth",
        "query": queue_query,
        "threshold": str(a.queue_depth_target)}}
    # percent integer, not a float ratio: the Helm template
    # must render the identical string without float math
    ttft_trigger = {"type": "prometheus", "metadata": {
        "serverAddress": spec.prometheus_url,
        "metricName": "llm_slo_ttft_miss_ratio",
        "query": "100 * max(llm_slo_ttft_miss_ratio)",
        "threshold":
            str(int(round((1.0 - a.ttft_ok_ratio_floor)
                          * 100)))}}
    if m.role == "prefill":
        triggers = [queue_trigger]
    elif m.role == "decode":
        triggers = [ttft_trigger]
    else:
        triggers = [queue_trigger, ttft_trigger]
    return {
        "apiVersion": "keda.sh/v1alpha1",
        "kind": "ScaledObject",
        "metadata": _meta(name, spec, "autoscaler"),
        "spec": {
            "scaleTargetRef": {"name": name},
            "minReplicaCount": a.min_replicas,
            "maxReplicaCount": a.max_replicas,
            "cooldownPeriod": SCALE_DOWN_STABILIZATION_S,
            "triggers": triggers,
        },
    }


def render_model_pvc(m: ModelSpec, spec: DeploySpec) -> Optional[Manifest]:
    if not m.huggingface_id:
        return None
    access = "ReadOnlyMany" if m.pvc_shared else "ReadWriteOnce"
    pvc: Manifest = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": _meta(f"{_res_name(m)}-cache", spec, "weight-cache"),
        "spec": {
            "accessModes": [access],
            "resources": {"requests": {"storage": m.pvc_size}},
        },
    }
    if spec.storage_class:
        pvc["spec"]["storageClassName"] = spec.storage_class
    return pvc


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

def _backend_urls(m: ModelSpec, spec: DeploySpec) -> list[str]:
    """Replica-set URLs for one models[] entry (always a list)."""
    if _peak_replicas(m) > 1 and not (m.tpu is not None and m.tpu.multi_host):
        # replicated single-host model: route via the headless -replicas
        # Service, whose DNS answers with the READY pod IPs (Deployment
        # pods have no stable per-pod names to enumerate). Explicit
        # multi-URL replica lists remain a config-level capability for
        # out-of-cluster replicas.
        return [f"http://{_res_name(m)}-replicas."
                f"{spec.namespace}.svc.cluster.local:{ENGINE_PORT}"]
    return [f"http://{_res_name(m)}."
            f"{spec.namespace}.svc.cluster.local:{ENGINE_PORT}"]


def router_config(spec: DeploySpec) -> dict[str, Any]:
    """The router's model→replica-set table (consumed by server/router.py
    and by the native C++ router alike)."""
    # a disaggregated pair shares its modelName: both entries' URLs merge
    # into one replica set, and the roles map tells the router which URL
    # serves which hop of the two-hop flow
    backends: dict[str, list[str]] = {}
    roles: dict[str, str] = {}
    for m in spec.models:
        urls = _backend_urls(m, spec)
        backends.setdefault(m.model_name, []).extend(urls)
        if m.role != "both":
            for u in urls:
                roles[u] = m.role
    cfg: dict[str, Any] = {
        "backends": backends,
        "default_model": spec.resolved_default,
        "strict": spec.strict_routing,
        "probe_interval_s": spec.probe_interval_s,
        # zero-drop streams: journal resume + hedging knobs (ISSUE 9),
        # parsed by both router implementations
        "stream_resume": spec.stream_resume,
        "resume_attempts": spec.resume_attempts,
        "hedge_ms": spec.hedge_ms,
    }
    if roles:
        cfg["roles"] = roles
        cfg["handoff_retries"] = spec.handoff_retries
    adapters = {m.model_name: [a.name for a in m.adapters]
                for m in spec.models if m.adapters}
    if adapters:
        # base:adapter requests resolve at the gateway; unknown adapters
        # of a known base 404 instead of falling back to the base model
        cfg["adapters"] = adapters
    if spec.qos is not None:
        # per-tenant QoS (ISSUE 10): fair shares, rate limits, brownout —
        # identical wire keys for both router implementations
        cfg["qos"] = spec.qos.to_wire()
    if spec.outlier_ejection is not None:
        # gray-failure layer (ISSUE 17): latency/error outlier ejection —
        # a non-empty block enables it in both router implementations
        cfg["outlier_ejection"] = spec.outlier_ejection.to_wire()
    if spec.retry_budget is not None:
        cfg["retry_budget"] = spec.retry_budget.to_wire()
    if spec.prefix_affinity is not None:
        # prefix-affinity + cache-aware routing (ISSUE 18): a non-empty
        # block enables the layer in both router implementations
        cfg["prefix_affinity"] = spec.prefix_affinity.to_wire()
    if spec.tracing is not None:
        # cross-hop tracing (ISSUE 19): OTLP export + tail sampling — a
        # non-empty block enables the exporter in both implementations
        # (traceparent propagation itself needs no config)
        cfg["tracing"] = spec.tracing.to_wire()
    return cfg


def config_hash(spec: DeploySpec) -> str:
    blob = json.dumps(router_config(spec), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def render_router(spec: DeploySpec) -> list[Manifest]:
    cfg = router_config(spec)
    cm = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": _meta("api-gateway-config", spec, "router-config"),
        "data": {"router.json": json.dumps(cfg, indent=2, sort_keys=True)},
    }
    args = ["router", "--config", "/etc/router/router.json"]
    dep = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta("api-gateway", spec, "router"),
        "spec": {
            "replicas": 2,   # the reference's only redundancy was its python
                             # gateway's 2 replicas (api-gateway.yaml:121)
            "selector": {"matchLabels": {"app": "api-gateway"}},
            "template": {
                "metadata": {
                    "labels": _labels("api-gateway", "router"),
                    # config-hash annotation: rolls the router pods whenever
                    # the models[] list changes (reference gap, SURVEY §3.2)
                    "annotations": {
                        "checksum/router-config": config_hash(spec),
                        **_scrape_annotations(ROUTER_PORT),
                    },
                },
                "spec": {
                    "terminationGracePeriodSeconds": ROUTER_GRACE_S,
                    "containers": [{
                        "name": "router",
                        "image": spec.image,
                        "imagePullPolicy": spec.image_pull_policy,
                        **_lifecycle(),
                        "command": (
                            ["/usr/local/bin/tpu-router"] if spec.native_router
                            else ["python", "-m", "llms_on_kubernetes_tpu"]
                        ),
                        "args": args,
                        "ports": [{"containerPort": ROUTER_PORT, "name": "http"}],
                        "volumeMounts": [{
                            "name": "config", "mountPath": "/etc/router",
                        }],
                        "readinessProbe": {
                            "httpGet": {"path": "/health", "port": ROUTER_PORT},
                            "initialDelaySeconds": 2, "periodSeconds": 5,
                        },
                        "livenessProbe": {
                            "httpGet": {"path": "/health", "port": ROUTER_PORT},
                            "initialDelaySeconds": 10, "periodSeconds": 20,
                        },
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "128Mi"},
                            "limits": {"cpu": "1", "memory": "512Mi"},
                        },
                    }],
                    "volumes": [{
                        "name": "config",
                        "configMap": {"name": "api-gateway-config"},
                    }],
                },
            },
        },
    }
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta("api-gateway", spec, "router"),
        "spec": {
            "type": "ClusterIP",
            "selector": {"app": "api-gateway"},
            "ports": [{"port": ROUTER_PORT, "targetPort": ROUTER_PORT,
                       "name": "http"}],
        },
    }
    return [cm, dep, svc]


# ---------------------------------------------------------------------------
# Istio + WebUI
# ---------------------------------------------------------------------------

def render_istio(spec: DeploySpec) -> list[Manifest]:
    gw = {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "Gateway",
        "metadata": _meta("tpu-models-gateway", spec, "ingress"),
        "spec": {
            "selector": {"istio": "ingressgateway"},
            "servers": [{
                "port": {"number": 80, "name": "http", "protocol": "HTTP"},
                "hosts": ["*"],
            }],
        },
    }
    gateway_dst = [{"destination": {
        "host": f"api-gateway.{spec.namespace}.svc.cluster.local",
        "port": {"number": ROUTER_PORT}}}]
    routes = [
        {"match": [{"uri": {"exact": "/v1/models"}}], "route": gateway_dst},
        {"match": [{"uri": {"prefix": "/v1/"}}], "route": gateway_dst},
        {"match": [{"uri": {"prefix": "/health"}}], "route": gateway_dst},
    ]
    if spec.webui_enabled:
        routes.append({
            "match": [{"uri": {"prefix": "/"}}],
            "route": [{"destination": {
                "host": f"webui.{spec.namespace}.svc.cluster.local",
                "port": {"number": WEBUI_PORT}}}],
        })
    vs = {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": _meta("tpu-models-routes", spec, "ingress"),
        "spec": {
            "hosts": ["*"],
            "gateways": ["tpu-models-gateway"],
            "http": routes,
        },
    }
    return [gw, vs]


def render_webui(spec: DeploySpec) -> list[Manifest]:
    if not spec.webui_enabled:
        return []
    dep = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta("webui", spec, "webui"),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "webui"}},
            "template": {
                "metadata": {
                    "labels": _labels("webui", "webui"),
                    # explicit opt-out: OpenWebUI exposes no Prometheus
                    # endpoint, so the annotation documents the decision
                    # instead of leaving the pod silently unscraped
                    "annotations": {"prometheus.io/scrape": "false"},
                },
                "spec": {
                    "containers": [{
                        "name": "webui",
                        "image": "ghcr.io/open-webui/open-webui:dev-slim",
                        "env": [
                            {"name": "OPENAI_API_BASE_URLS", "value":
                                f"http://api-gateway.{spec.namespace}.svc.cluster.local:{ROUTER_PORT}/v1"},
                            {"name": "WEBUI_NAME", "value": spec.webui_name},
                        ],
                        "ports": [{"containerPort": WEBUI_PORT}],
                        "volumeMounts": [{
                            "name": "data", "mountPath": "/app/backend/data",
                        }],
                        "readinessProbe": {
                            "httpGet": {"path": "/", "port": WEBUI_PORT},
                            "initialDelaySeconds": 15, "periodSeconds": 10,
                        },
                        "resources": {
                            "requests": {"cpu": "200m", "memory": "512Mi"},
                            "limits": {"cpu": "1", "memory": "1Gi"},
                        },
                    }],
                    "volumes": [{
                        "name": "data",
                        "persistentVolumeClaim": {"claimName": "webui-data"},
                    }],
                },
            },
        },
    }
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta("webui", spec, "webui"),
        "spec": {
            "type": "ClusterIP",
            "selector": {"app": "webui"},
            "ports": [{"port": WEBUI_PORT, "targetPort": WEBUI_PORT}],
        },
    }
    pvc = {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        # chat history survives chart deletion, like the reference's
        # `helm.sh/resource-policy: keep` (webui-pvc.yaml:8-9)
        "metadata": _meta("webui-data", spec, "webui",
                          annotations={"helm.sh/resource-policy": "keep"}),
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": "1Gi"}},
        },
    }
    return [dep, svc, pvc]


# ---------------------------------------------------------------------------
# Top level
# ---------------------------------------------------------------------------

def render_manifests(spec: DeploySpec) -> list[Manifest]:
    spec.validate()
    out: list[Manifest] = []
    for m in spec.models:
        if m.tpu is not None and m.tpu.multi_host:
            out += render_model_multi_host(m, spec)
        else:
            out += render_model_single_host(m, spec)
        out.append(render_model_service(m, spec))
        replica_svc = render_model_replica_service(m, spec)
        if replica_svc:
            out.append(replica_svc)
        pvc = render_model_pvc(m, spec)
        if pvc:
            out.append(pvc)
        hpa = render_model_autoscaler(m, spec)
        if hpa:
            out.append(hpa)
    out += render_router(spec)
    out += render_istio(spec)
    out += render_webui(spec)
    out += render_monitoring_manifests(spec)
    return out


def render_monitoring_manifests(spec: DeploySpec) -> list[Manifest]:
    """Alert-rules + Grafana-dashboard ConfigMaps (deploy.monitoring is
    the source of truth; deferred import keeps this module importable
    without pulling the dashboard payloads in)."""
    from llms_on_kubernetes_tpu.deploy.monitoring import render_monitoring

    return render_monitoring(spec)


def to_yaml(manifests: list[Manifest]) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False)
        for m in manifests
    )

"""Deployment layer: the reference's Helm/GitOps packaging, TPU-native.

The reference's real public API is its Helm ``models[]`` values contract
(reference vllm-models/helm-chart/values.yaml:1-27) fanned out into per-model
Deployments/Services/PVCs, a routing gateway, Istio ingress, and a WebUI
(SURVEY §3.2). Here that contract lives in ``spec.py`` (validated dataclasses
— the values.schema.json the reference lacked) and ``manifests.py`` (a pure
renderer emitting the same resource set with TPU scheduling:
``google.com/tpu`` requests, GKE TPU nodeSelectors, multi-host slice
topologies). ``charts/`` at the repo root carries the equivalent Helm chart
for ArgoCD-based GitOps sync.
"""

from llms_on_kubernetes_tpu.deploy.spec import (  # noqa: F401
    DeploySpec, ModelSpec, ShardingSpec, TPUSpec, load_spec,
)
from llms_on_kubernetes_tpu.deploy.manifests import render_manifests, to_yaml  # noqa: F401

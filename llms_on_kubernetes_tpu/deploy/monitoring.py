"""Shipping monitoring artifacts: Prometheus alert rules + Grafana dashboard.

Telemetry that nobody alerts on is a dashboard screenshot, not monitoring.
This module is the single source of truth for the two artifacts the
deployment ships alongside the serving topology:

- ``alert_rules()``: a Prometheus rule file (dict form) with the
  multi-window SLO burn-rate alerts over the router's ``llm_slo_*``
  gauges, a wedged-engine page on ``llm_engine_state``, replica-health
  and cluster-scrape-error tickets;
- ``grafana_dashboard()``: a Grafana dashboard (dict form) for the same
  series plus the runtime telemetry (device memory, compile cache,
  kernel-vs-host step split) added in this PR.

``render_monitoring(spec)`` wraps both in ConfigMaps so
``deploy.manifests.render_manifests`` ships them with everything else.
The Helm charts carry byte-identical copies under
``helm-chart/files/`` (templates/monitoring.yaml mounts them via
``.Files.Get``); ``scripts/check_monitoring.py`` regenerates those
copies and CI fails if they drift from this module.

Every ``llm_*`` name referenced by an alert expression must be a series
this repo actually emits — ``scripts/check_monitoring.py`` cross-checks
them against ``scripts/metrics_lint.known_emitted_names()`` so a renamed
metric can't silently orphan its alert.
"""

from __future__ import annotations

import json
import re
from typing import Any

ALERT_RULES_CONFIGMAP = "llmk-alert-rules"
ALERT_RULES_KEY = "llmk-alerts.yaml"
DASHBOARD_CONFIGMAP = "llmk-grafana-dashboard"
DASHBOARD_KEY = "llmk-dashboard.json"

_METRIC_NAME_RE = re.compile(r"\bllm_[a-z0-9_]+")


def alert_rules() -> dict[str, Any]:
    """Prometheus rule file covering the SLOs and the failure modes the
    fault-tolerance PRs introduced detection for.

    Burn-rate thresholds follow the standard multi-window pairing
    (SRE workbook ch.5): a fast window that pages when the monthly
    budget would be gone in hours, and a slow window that tickets a
    steady leak. The ``llm_slo_*`` gauges are already windowed by the
    router (LLMK_SLO_WINDOW_S), so the rules use plain ``for:`` holds
    rather than recording-rule window math.
    """
    return {
        "groups": [
            {
                "name": "llmk-slo",
                "rules": [
                    {
                        "alert": "LLMKErrorBudgetFastBurn",
                        "expr": "llm_slo_error_budget_burn_rate > 14",
                        "for": "5m",
                        "labels": {"severity": "page"},
                        "annotations": {
                            "summary": "error budget burning >14x",
                            "description": (
                                "Availability error budget on "
                                "{{ $labels.instance }} is burning at "
                                "{{ $value }}x the sustainable rate; at "
                                "14x a 30-day budget is gone in ~2 days."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKErrorBudgetSlowBurn",
                        "expr": "llm_slo_error_budget_burn_rate > 2",
                        "for": "1h",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "error budget burning >2x",
                            "description": (
                                "Sustained burn rate {{ $value }}x on "
                                "{{ $labels.instance }} will exhaust the "
                                "budget well before the window ends."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKTTFTObjectiveMissed",
                        "expr": "llm_slo_ttft_ok_ratio < 0.95",
                        "for": "10m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "TTFT SLO below target",
                            "description": (
                                "Only {{ $value }} of recent requests met "
                                "the TTFT objective (target 0.95) on "
                                "{{ $labels.instance }}."
                            ),
                        },
                    },
                ],
            },
            {
                "name": "llmk-serving",
                "rules": [
                    {
                        "alert": "LLMKEngineWedged",
                        # state enum: 0 idle, 1 active, 2 draining, 3 wedged
                        # (server/metrics.py llm_engine_state)
                        "expr": "llm_engine_state == 3",
                        "for": "1m",
                        "labels": {"severity": "page"},
                        "annotations": {
                            "summary": "engine wedged (watchdog)",
                            "description": (
                                "Engine on {{ $labels.instance }} has "
                                "reported the wedged state for 1m; decode "
                                "progress has stalled past the watchdog "
                                "budget."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKReplicaUnhealthy",
                        "expr": "llm_replica_healthy == 0",
                        "for": "2m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "replica failing health probes",
                            "description": (
                                "Router marks replica "
                                "{{ $labels.replica }} of model "
                                "{{ $labels.model }} unhealthy; traffic "
                                "is failing over to peers."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKClusterScrapeErrors",
                        "expr": (
                            "rate(llm_cluster_scrape_errors_total[5m])"
                            " > 0"
                        ),
                        "for": "10m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "cluster metrics aggregation "
                                       "degraded",
                            "description": (
                                "/metrics/cluster on "
                                "{{ $labels.instance }} has been failing "
                                "to scrape at least one replica for 10m; "
                                "the merged view is incomplete."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKAdapterThrash",
                        "expr": (
                            "rate(llm_adapter_cache_evictions_total[5m])"
                            " > 0.5"
                        ),
                        "for": "10m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "LoRA adapter cache thrashing",
                            "description": (
                                "Engine on {{ $labels.instance }} is "
                                "evicting adapters faster than one every "
                                "two seconds for 10m; the working set of "
                                "adapters exceeds the device slots "
                                "(raise adapterSlots or split tenants)."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKKVHostCacheThrash",
                        # pages churn through the host tier without ever
                        # being re-used: the parked-session working set
                        # exceeds the tier, so spills evict pages faster
                        # than resumes consume them
                        "expr": (
                            "rate(llm_kv_host_cache_evictions_total[10m])"
                            " > 1 and rate("
                            "llm_kv_host_cache_hits_total[10m]) < 0.1 * "
                            "rate(llm_kv_host_cache_evictions_total[10m])"
                        ),
                        "for": "15m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "host KV offload tier thrashing",
                            "description": (
                                "Engine on {{ $labels.instance }} is "
                                "evicting host-tier KV pages much faster "
                                "than resuming sessions re-use them; "
                                "parked sessions age out before they "
                                "return. Raise kvHostCacheGB (and the "
                                "pod memory request) or accept "
                                "re-prefills for long-idle sessions."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKColdStartSlow",
                        # phase="ready" is process start -> taking
                        # traffic; with the persistent compile cache a
                        # warm restart should be far under this
                        "expr": (
                            "histogram_quantile(0.95, rate("
                            'llm_cold_start_seconds_bucket'
                            '{phase="ready"}[30m])) > 180'
                        ),
                        "for": "5m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "replicas starting too slowly "
                                       "to absorb spikes",
                            "description": (
                                "p95 cold start (start to ready) is "
                                "{{ $value }}s over the last 30m; "
                                "scale-out arrives too late to help a "
                                "spike. Check the persistent compile "
                                "cache (LLMK_COMPILE_CACHE_DIR on the "
                                "weight PVC) and weight-load times."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKQueueSaturated",
                        # 2x the default autoscaling queueDepthTarget (8)
                        "expr": "llm_queue_depth > 16",
                        "for": "10m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "admission queue saturated",
                            "description": (
                                "Model {{ $labels.model }} on "
                                "{{ $labels.instance }} has held more "
                                "than twice the autoscaling target of "
                                "queued requests for 10m; the "
                                "autoscaler is at its ceiling, not "
                                "reacting, or scale-out is too slow."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKHandoffDegraded",
                        # disaggregated prefill/decode only: handoffs
                        # that miss the fast path — decode re-prefilling
                        # from scratch or the router falling back to a
                        # colocated replica — still serve correctly, but
                        # burn the chip-time disaggregation was meant to
                        # save. A sustained degraded fraction means the
                        # host tier is evicting pages before adoption or
                        # the decode pool is unreachable.
                        "expr": (
                            "sum(rate(llm_handoff_total{outcome=~"
                            '"reprefill|fallback_colocated"}[10m])) > '
                            "0.2 * sum(rate(llm_handoff_total[10m]))"
                        ),
                        "for": "15m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "KV handoffs degrading to "
                                       "re-prefill / colocated fallback",
                            "description": (
                                "More than 20% of prefill->decode KV "
                                "handoffs have missed the fast path for "
                                "15m (llm_handoff_total outcomes "
                                "reprefill + fallback_colocated). "
                                "Streams still complete, but decode "
                                "replicas are repeating prefill work. "
                                "Check decode-pool health/breakers, "
                                "kvHostCacheGB pressure on the prefill "
                                "pool, and LLMK_HANDOFF_RETRIES."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKStreamLoss",
                        # any truncation at all is a client that watched
                        # its generation die — the journal/resume path
                        # exists precisely so this stays at zero
                        "expr": (
                            "increase(llm_stream_truncated_total[10m]) > 0"
                        ),
                        "for": "10m",
                        "labels": {"severity": "page"},
                        "annotations": {
                            "summary": "client-visible stream truncations",
                            "description": (
                                "Streams for model {{ $labels.model }} "
                                "were truncated mid-generation for 10m "
                                "(upstream died and no resume was "
                                "possible). Check replica churn and "
                                "llm_stream_resume_total{outcome="
                                "\"gave_up\"} — exhausted resume "
                                "attempts, expired deadlines, or "
                                "non-resumable streams (multi-choice/"
                                "logprobs) are the usual causes."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKTenantStarvation",
                        # the fair scheduler's whole contract: no tenant
                        # waits unboundedly while peers are served. p99
                        # admission wait far beyond the starvation-aging
                        # threshold (qos_starvation_s, default 5s) means
                        # weights/priorities are misconfigured or the
                        # fleet is undersized for the admitted mix
                        "expr": (
                            "histogram_quantile(0.99, sum by (le, tenant)"
                            " (rate("
                            "llm_tenant_queue_wait_seconds_bucket[5m])))"
                            " > 30"
                        ),
                        "for": "10m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "tenant starving in the "
                                       "admission queue",
                            "description": (
                                "Tenant {{ $labels.tenant }} has a p99 "
                                "queue wait above 30s for 10m while the "
                                "engine keeps admitting; its fair-share "
                                "weight is too small for its load, or "
                                "higher-priority traffic plus brownout "
                                "shedding is not relieving pressure."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKSpecLowAccept",
                        # advisory: speculation is burning verify FLOPs
                        # without paying for itself. The engine demotes
                        # drafting adaptively, so this is a tuning
                        # signal (switch ngram <-> draft tier, or turn
                        # speculation off for this traffic), never a
                        # correctness problem — greedy outputs are
                        # bit-identical either way.
                        "expr": (
                            "rate(llm_spec_accepted_total[15m]) / "
                            "rate(llm_spec_drafted_total[15m]) < 0.2 "
                            "and rate(llm_spec_drafted_total[15m]) > 1"
                        ),
                        "for": "30m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "speculative decoding accept "
                                       "ratio persistently low",
                            "description": (
                                "Fewer than 20% of drafted tokens on "
                                "{{ $labels.instance }} survive the "
                                "verify pass over 30m. The drafter does "
                                "not fit this traffic; consider the "
                                "draft-model tier (draft:) for free-form "
                                "chat, prompt-lookup (speculation: "
                                "ngram) for RAG/code/summarization, or "
                                "disabling speculation for this model."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKGoodputCollapse",
                        # the goodput ledger's headline failure mode:
                        # chips are busy (or idle) but streams are not
                        # being served — most chip time going to waste
                        # phases or idle gaps WHILE work is queued. The
                        # per-phase breakdown and any auto-profile
                        # capture (llm_auto_profile_total) say where the
                        # time went.
                        "expr": (
                            "sum(rate(llm_chip_seconds_total"
                            '{phase=~"spec_waste|early_exit|idle"}[10m]))'
                            " / sum(rate(llm_chip_seconds_total[10m]))"
                            " > 0.6 and on() sum(llm_queue_depth) > 4"
                        ),
                        "for": "10m",
                        "labels": {"severity": "page"},
                        "annotations": {
                            "summary": "chip time mostly wasted/idle "
                                       "while requests queue",
                            "description": (
                                "Over 60% of ledger chip-seconds are "
                                "speculative rejected tails, early-exit "
                                "rows, or idle gaps for 10m while the "
                                "admission queue is non-empty. Serving "
                                "capacity is being burned without "
                                "producing stream tokens: check the "
                                "spec accept ratio, decode_steps sizing "
                                "vs typical generations, and the "
                                "flight recorder / auto-profile capture "
                                "for the slow phase."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKReplicaQuarantined",
                        # gray failure: the replica answers health probes
                        # but its in-band TTFT/error EWMA is a z-score
                        # outlier vs peers, so the router quarantined it.
                        # Traffic fails over; a ticket because the pod
                        # needs a human look (probes will NOT catch it)
                        "expr": "llm_replica_quarantined == 1",
                        "for": "5m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "replica quarantined as a "
                                       "gray-failure outlier",
                            "description": (
                                "Router quarantined replica "
                                "{{ $labels.replica }} of model "
                                "{{ $labels.model }} as a "
                                "{{ $labels.reason }} outlier vs its "
                                "peers while its health probes stayed "
                                "green. Shadow traffic will readmit it "
                                "if it recovers; a quarantine that "
                                "holds for hours is a degraded pod "
                                "(bad node, throttled NIC, sick HBM) "
                                "that needs replacing."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKRetryBudgetExhausted",
                        # sustained exhaustion = the cluster is in (or
                        # one failover away from) a retry storm: enough
                        # primaries are failing that the budget cannot
                        # cover their retries. Page — this is the
                        # metastable-failure guard actively shedding
                        "expr": (
                            "rate(llm_retry_budget_exhausted_total[5m])"
                            " > 0.1"
                        ),
                        "for": "10m",
                        "labels": {"severity": "page"},
                        "annotations": {
                            "summary": "retry budget exhausted — "
                                       "requests shedding instead of "
                                       "retrying",
                            "description": (
                                "The router on {{ $labels.instance }} "
                                "has been refusing retries (code="
                                "retry_budget_exhausted) for 10m: "
                                "failures are arriving faster than the "
                                "budget refills, the signature of a "
                                "fleet-wide problem a retry storm "
                                "would only amplify. Find the failing "
                                "replicas (llm_replica_quarantined, "
                                "llm_replica_healthy, breaker states) "
                                "instead of raising the budget."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKAffinityDegraded",
                        # cache-aware routing is configured but most
                        # keyed requests are falling back to plain P2C:
                        # pinned replicas unhealthy/quarantined/hot, or
                        # prompts that never produce a key. Prefill is
                        # being re-paid across the fleet — a ticket, not
                        # a page: serving still works, just slower.
                        "expr": (
                            "sum(rate(llm_affinity_fallback_total[15m]))"
                            " / (sum(rate(llm_affinity_hits_total[15m]))"
                            " + sum(rate("
                            "llm_affinity_fallback_total[15m])))"
                            " > 0.5"
                        ),
                        "for": "15m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "prefix-affinity routing mostly "
                                       "falling back to P2C",
                            "description": (
                                "Over half of affinity-keyed requests "
                                "fell back to plain P2C for 15m, so "
                                "prefix caches are going cold and "
                                "prefill is re-paid. Break down "
                                "llm_affinity_fallback_total by reason: "
                                "unhealthy/quarantined pins mean sick "
                                "replicas, overloaded means the pool is "
                                "too hot for pinning, miss means the "
                                "workload's prompts never form a key "
                                "(consider disabling the layer)."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKTraceDropping",
                        # the tail sampler guarantees errors/slow/multi-
                        # hop traces always export; drops beyond the
                        # deliberate reasons (sampled_out, disabled)
                        # mean the exporter queue is overflowing or the
                        # collector is rejecting batches — waterfalls
                        # for exactly the requests being debugged go
                        # missing. Ticket: observability gap, not an
                        # availability problem.
                        "expr": (
                            "sum(rate(llm_trace_dropped_total"
                            "{reason=\"queue_full\"}[10m]))"
                            " + sum(rate(llm_trace_spans_exported_total"
                            "{outcome=\"error\"}[10m])) > 0.1"
                        ),
                        "for": "10m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "trace spans being dropped — "
                                       "waterfalls incomplete",
                            "description": (
                                "Trace export on {{ $labels.instance }} "
                                "is losing spans: the OTLP queue is "
                                "overflowing (reason=queue_full) or the "
                                "collector is rejecting batches "
                                "(outcome=error). Tail-sampled traces "
                                "(errors, slow, multi-hop) are exactly "
                                "the ones an investigation needs. Check "
                                "collector health and the "
                                "tracing.otlpEndpoint value; lower "
                                "tracing.sample if volume is the cause."
                            ),
                        },
                    },
                    {
                        "alert": "LLMKDeadlineExceeded",
                        "expr": (
                            "rate(llm_deadline_exceeded_total[5m]) > 1"
                        ),
                        "for": "5m",
                        "labels": {"severity": "ticket"},
                        "annotations": {
                            "summary": "requests blowing their deadline",
                            "description": (
                                "More than one request per second on "
                                "{{ $labels.instance }} is exceeding its "
                                "end-to-end deadline."
                            ),
                        },
                    },
                ],
            },
        ],
    }


def _panel(panel_id: int, title: str, exprs: list[str],
           x: int, y: int, unit: str = "short") -> dict[str, Any]:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}},
        "targets": [
            {"expr": expr, "refId": chr(ord("A") + i)}
            for i, expr in enumerate(exprs)
        ],
    }


def grafana_dashboard() -> dict[str, Any]:
    """One dashboard, four rows: SLO health, traffic/latency, engine
    runtime (device memory + compile cache + step split), and fleet
    state. Expressions stick to series validated by check_monitoring."""
    panels = [
        _panel(1, "SLO: availability / TTFT ok ratio",
               ["llm_slo_availability", "llm_slo_ttft_ok_ratio"],
               0, 0, unit="percentunit"),
        _panel(2, "SLO: error budget burn rate",
               ["llm_slo_error_budget_burn_rate"], 12, 0),
        _panel(3, "Request rate",
               ["rate(llm_requests_total[5m])",
                "rate(llm_requests_finished_total[5m])"], 0, 8,
               unit="reqps"),
        _panel(4, "TTFT p50/p95",
               ["histogram_quantile(0.5, "
                "rate(llm_ttft_seconds_bucket[5m]))",
                "histogram_quantile(0.95, "
                "rate(llm_ttft_seconds_bucket[5m]))"], 12, 8,
               unit="s"),
        _panel(5, "Device memory",
               ['llm_device_memory_bytes{kind="bytes_in_use"}',
                'llm_device_memory_bytes{kind="bytes_limit"}',
                "llm_device_live_buffer_bytes"], 0, 16,
               unit="bytes"),
        _panel(6, "JIT compiles vs cache hits",
               ["rate(llm_jit_compiles_total[5m])",
                "rate(llm_jit_cache_hits_total[5m])"], 12, 16),
        _panel(7, "Step time split: device vs host",
               ["rate(llm_step_device_seconds_total[5m])",
                "rate(llm_step_host_seconds_total[5m])"], 0, 24,
               unit="percentunit"),
        _panel(8, "Fleet: replica health / engine state",
               ["llm_replica_healthy", "llm_engine_state",
                "llm_cluster_replica_up"], 12, 24),
        _panel(9, "Tokens generated",
               ["rate(llm_tokens_generated_total[5m])"], 0, 32),
        _panel(10, "KV pages used / waiting requests",
               ["llm_kv_pages_used", "llm_waiting_requests"], 12, 32),
        _panel(11, "Adapter cache: hits / misses / evictions",
               ["rate(llm_adapter_cache_hits_total[5m])",
                "rate(llm_adapter_cache_misses_total[5m])",
                "rate(llm_adapter_cache_evictions_total[5m])"], 0, 40),
        _panel(12, "Adapter load latency p95",
               ["histogram_quantile(0.95, "
                "rate(llm_adapter_load_seconds_bucket[5m]))"], 12, 40,
               unit="s"),
        _panel(13, "Cold start by phase (p95)",
               ["histogram_quantile(0.95, sum by (le, phase) "
                "(rate(llm_cold_start_seconds_bucket[30m])))"], 0, 48,
               unit="s"),
        _panel(14, "Queue depth per model (autoscaling signal)",
               ["llm_queue_depth",
                "rate(llm_router_requests_total[1m])"], 12, 48),
        _panel(15, "Decode fusion: steps/dispatch p50 / early-exit rate",
               ["histogram_quantile(0.5, "
                "rate(llm_decode_steps_per_dispatch_bucket[5m]))",
                "rate(llm_decode_early_exit_total[5m])"], 0, 56),
        _panel(16, "Stream resilience: resumes / hedges / truncations",
               ["rate(llm_stream_resume_total[5m])",
                "rate(llm_hedged_requests_total[5m])",
                "rate(llm_stream_truncated_total[5m])"], 12, 56),
        _panel(17, "QoS: shed by priority (gateway + engine)",
               ["sum by (priority) "
                "(rate(llm_tenant_router_shed_total[5m]))",
                "sum by (priority) (rate(llm_tenant_shed_total[5m]))",
                "sum by (priority) "
                "(rate(llm_tenant_degraded_total[5m]))"], 0, 64),
        _panel(18, "QoS: per-tenant queue wait p95 / admissions",
               ["histogram_quantile(0.95, sum by (le, tenant) "
                "(rate(llm_tenant_queue_wait_seconds_bucket[5m])))",
                "sum by (tenant) "
                "(rate(llm_tenant_admitted_total[5m]))"], 12, 64,
               unit="s"),
        _panel(19, "Speculative decode: accept ratio",
               ["llm_spec_accept_ratio",
                "rate(llm_spec_accepted_total[5m]) / "
                "rate(llm_spec_drafted_total[5m])"], 0, 72,
               unit="percentunit"),
        _panel(20, "Speculative decode: drafted / accepted rate",
               ["rate(llm_spec_drafted_total[5m])",
                "rate(llm_spec_accepted_total[5m])"], 12, 72),
        _panel(21, "KV host tier: hit ratio / evictions",
               ["rate(llm_kv_host_cache_hits_total[5m]) / "
                "(rate(llm_kv_host_cache_hits_total[5m]) + "
                "rate(llm_kv_host_cache_misses_total[5m]))",
                "rate(llm_kv_host_cache_evictions_total[5m])"], 0, 80),
        _panel(22, "KV: upload p95 / bytes per token",
               ["histogram_quantile(0.95, "
                "rate(llm_kv_upload_seconds_bucket[5m]))",
                "llm_kv_bytes_per_token"], 12, 80),
        _panel(23, "Goodput: chip-seconds by phase",
               ['sum by (phase) (rate(llm_chip_seconds_total[5m]))'],
               0, 88, unit="percentunit"),
        _panel(24, "Hardware utilization: MFU / MBU",
               ["llm_mfu_ratio", "llm_mbu_ratio"], 12, 88,
               unit="percentunit"),
        _panel(25, "Wasted chip fraction (spec tails + early exits)",
               ['sum (rate(llm_chip_seconds_total'
                '{phase=~"spec_waste|early_exit"}[5m])) / '
                'sum (rate(llm_chip_seconds_total[5m]))'],
               0, 96, unit="percentunit"),
        _panel(26, "Per-tenant chip-seconds (chargeback)",
               ["sum by (tenant) "
                "(rate(llm_tenant_chip_seconds_total[5m]))",
                "rate(llm_auto_profile_total[1h])"], 12, 96),
        _panel(27, "KV handoff: outcomes (disaggregated)",
               ["sum by (outcome) (rate(llm_handoff_total[5m]))"],
               0, 104),
        _panel(28, "KV handoff: latency p50 / p95",
               ["histogram_quantile(0.50, "
                "rate(llm_handoff_seconds_bucket[5m]))",
                "histogram_quantile(0.95, "
                "rate(llm_handoff_seconds_bucket[5m]))"], 12, 104,
               unit="s"),
        _panel(29, "Gray failure: quarantined replicas / ejections",
               ["sum by (model, replica, reason) "
                "(llm_replica_quarantined)",
                "sum by (reason) "
                "(rate(llm_outlier_ejections_total[5m]))"], 0, 112),
        _panel(30, "Retry budget: exhaustion rate",
               ["rate(llm_retry_budget_exhausted_total[5m])"], 12, 112),
        _panel(31, "Prefix affinity: cache-aware placements / fallbacks",
               ["sum by (model) (rate(llm_affinity_hits_total[5m]))",
                "sum by (model, reason) "
                "(rate(llm_affinity_fallback_total[5m]))"], 0, 120),
        _panel(32, "Prefix affinity: filter age (stale = blind routing)",
               ["max by (model, replica) "
                "(llm_prefix_filter_age_seconds)"], 12, 120, unit="s"),
        _panel(33, "Tracing: spans exported (by outcome)",
               ["sum by (outcome) "
                "(rate(llm_trace_spans_exported_total[5m]))"], 0, 128),
        _panel(34, "Tracing: traces dropped (by reason)",
               ["sum by (reason) "
                "(rate(llm_trace_dropped_total[5m]))"], 12, 128),
    ]
    return {
        "title": "LLM serving on TPU — cluster overview",
        "uid": "llmk-overview",
        "tags": ["llmk", "tpu", "slo"],
        "timezone": "utc",
        "schemaVersion": 39,
        "refresh": "30s",
        "time": {"from": "now-1h", "to": "now"},
        "panels": panels,
    }


def alert_rules_yaml() -> str:
    """Rule file as YAML text — the exact bytes shipped in the ConfigMap
    and committed under each chart's files/ directory."""
    import yaml

    return yaml.safe_dump(alert_rules(), sort_keys=False,
                          default_flow_style=False)


def dashboard_json() -> str:
    return json.dumps(grafana_dashboard(), indent=2, sort_keys=True) + "\n"


def referenced_metric_names() -> set[str]:
    """Every llm_* series name referenced by an alert expression or a
    dashboard panel target. check_monitoring verifies this set is a
    subset of what the servers actually emit."""
    names: set[str] = set()
    for group in alert_rules()["groups"]:
        for rule in group["rules"]:
            names.update(_METRIC_NAME_RE.findall(rule["expr"]))
    for panel in grafana_dashboard()["panels"]:
        for target in panel["targets"]:
            names.update(_METRIC_NAME_RE.findall(target["expr"]))
    return names


def render_monitoring(spec) -> list[dict[str, Any]]:
    """The two monitoring ConfigMaps, in the same Manifest dict form as
    the rest of deploy.manifests. The Grafana ConfigMap carries the
    conventional ``grafana_dashboard: "1"`` label that the Grafana
    sidecar provisioner watches for."""
    from llms_on_kubernetes_tpu.deploy.manifests import _meta

    alerts_cm = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": _meta(ALERT_RULES_CONFIGMAP, spec, "monitoring"),
        "data": {ALERT_RULES_KEY: alert_rules_yaml()},
    }
    dash_meta = _meta(DASHBOARD_CONFIGMAP, spec, "monitoring")
    dash_meta["labels"] = dict(dash_meta["labels"])
    dash_meta["labels"]["grafana_dashboard"] = "1"
    dashboard_cm = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": dash_meta,
        "data": {DASHBOARD_KEY: dashboard_json()},
    }
    return [alerts_cm, dashboard_cm]
